"""Streaming EC pipeline (ec/pipeline.py) — identity vs the synchronous path.

The pipeline must produce byte-identical shard files to striping.write_ec_files
for every geometry/batch-size combination (the schedule is the only thing that
changes), and stream_rebuild must reproduce the original shards exactly.
"""

import hashlib
import os
import random

import pytest

from seaweedfs_tpu import ec
from seaweedfs_tpu.ec import pipeline
from seaweedfs_tpu.storage.needle import Needle
from seaweedfs_tpu.storage.volume import Volume

GEO = ec.Geometry(data_shards=10, parity_shards=4,
                  large_block_size=10000, small_block_size=100)


def build_volume(tmp_path, n_needles=60, seed=3):
    os.makedirs(str(tmp_path), exist_ok=True)
    rng = random.Random(seed)
    v = Volume(str(tmp_path), "", 1, create=True)
    for i in range(1, n_needles + 1):
        data = bytes(rng.getrandbits(8) for _ in range(rng.randint(1, 1200)))
        v.write_needle(Needle(cookie=0x9000 + i, id=i, data=data))
    v.close()


def _sha(path):
    return hashlib.sha256(open(path, "rb").read()).hexdigest()


@pytest.mark.parametrize("batch_size", [64, 4096, 1 << 20])
def test_stream_encode_matches_sync(tmp_path, batch_size):
    # same .dat encoded through both paths (needle v3 timestamps make two
    # separately-built volumes differ)
    build_volume(tmp_path / "a")
    os.makedirs(str(tmp_path / "b"))
    base_a = os.path.join(str(tmp_path / "a"), "1")
    base_b = os.path.join(str(tmp_path / "b"), "1")
    with open(base_a + ".dat", "rb") as src, \
            open(base_b + ".dat", "wb") as dst:
        dst.write(src.read())
    coder = ec.get_coder("jax", 10, 4)
    ec.write_ec_files(base_a, coder, GEO, buffer_size=50)
    pipeline.stream_encode(base_b, coder, GEO, batch_size=batch_size)
    for i in range(14):
        assert _sha(base_a + ec.to_ext(i)) == _sha(base_b + ec.to_ext(i)), i


def test_stream_rebuild_roundtrip(tmp_path):
    build_volume(tmp_path)
    coder = ec.get_coder("jax", 10, 4)
    base = os.path.join(str(tmp_path), "1")
    pipeline.stream_encode(base, coder, GEO, batch_size=4096)
    golden = {i: _sha(base + ec.to_ext(i)) for i in range(14)}
    victims = [0, 5, 11, 13]
    for i in victims:
        os.remove(base + ec.to_ext(i))
    rebuilt = pipeline.stream_rebuild(base, coder, GEO, batch_size=512)
    assert sorted(rebuilt) == victims
    for i in range(14):
        assert _sha(base + ec.to_ext(i)) == golden[i], i


@pytest.mark.parametrize("dat_blocks", [
    # mixed-tier sizes in units of GEO.small_block (100B), chosen around the
    # large-row (10000B -> 1000 small units... here large=10000, small=100,
    # ratio=100, large_row=100000, small_row=1000) ambiguity window: a tail
    # needing a full large_block of small rows used to make k*shard_size
    # decode to the wrong large-row count (the reference's own layout has
    # this inconsistency, ec_locate.go:19-20 vs ec_encoder.go:57)
    99_000, 99_001, 100_000, 100_001, 152_000, 199_999, 200_000])
def test_mixed_tier_layout_consistency(tmp_path, dat_blocks):
    import numpy as np

    from seaweedfs_tpu.ec.locate import locate_data
    size = dat_blocks  # bytes
    rng = np.random.default_rng(size % 89)
    dat = rng.integers(0, 256, size, dtype=np.uint8).tobytes()
    base = os.path.join(str(tmp_path), "1")
    with open(base + ".dat", "wb") as f:
        f.write(dat)
    coder = ec.get_coder("numpy", 10, 4)
    ec.write_ec_files(base, coder, GEO, buffer_size=100)
    base2 = os.path.join(str(tmp_path), "2")
    with open(base2 + ".dat", "wb") as f:
        f.write(dat)
    pipeline.stream_encode(base2, coder, GEO, batch_size=1000)
    for i in range(14):
        assert _sha(base + ec.to_ext(i)) == _sha(base2 + ec.to_ext(i)), i
    # locate() addressing must read back the true bytes through shards
    shard_bytes = [open(base + ec.to_ext(i), "rb").read()
                   for i in range(10)]
    padded = 10 * os.path.getsize(base + ec.to_ext(0))
    for start, ln in ((0, min(size, 777)), (size // 2, 555),
                      (max(0, size - 999), 999)):
        ln = min(ln, size - start)
        got = b""
        for iv in locate_data(GEO, padded, start, ln):
            sid, o = iv.to_shard_id_and_offset(GEO)
            got += shard_bytes[sid][o:o + iv.size]
        assert got == dat[start:start + ln], (start, ln)
    # decode inverts encode
    os.remove(base + ".dat")
    ec.write_dat_file(base, size, GEO)
    assert open(base + ".dat", "rb").read() == dat


@pytest.mark.parametrize("coder_name", ["numpy", "jax", "pallas"])
def test_device_sink_digest_matches_shard_files(tmp_path, coder_name):
    # the on-device parity sink (bench mode) must be the same computation
    # as the file-writing path: its [m] uint32 wrapping byte-sum digest has
    # to equal the sums over the parity shard files stream_encode writes
    build_volume(tmp_path)
    coder = ec.get_coder(coder_name, 10, 4)
    base = os.path.join(str(tmp_path), "1")
    pipeline.stream_encode(base, coder, GEO, batch_size=4096)
    want = pipeline.parity_file_digest(base, GEO)
    got = pipeline.stream_encode_device_sink(base, coder, GEO,
                                             batch_size=4096)
    assert got.tolist() == want.tolist()
    # batch width must not change the combined digest
    got2 = pipeline.stream_encode_device_sink(base, coder, GEO,
                                              batch_size=512)
    assert got2.tolist() == want.tolist()


@pytest.mark.parametrize("coder_name", ["numpy", "jax", "pallas"])
def test_device_sink_windowed_schedule(tmp_path, coder_name):
    # a window smaller than the volume forces multiple window dispatches;
    # the chained digest must still equal the shard-file ground truth
    build_volume(tmp_path)
    coder = ec.get_coder(coder_name, 10, 4)
    base = os.path.join(str(tmp_path), "1")
    pipeline.stream_encode(base, coder, GEO, batch_size=4096)
    want = pipeline.parity_file_digest(base, GEO)
    stats = {}
    got = pipeline.stream_encode_device_sink(
        base, coder, GEO, batch_size=1024,
        window_bytes=10 * 1024, stats=stats)
    assert got.tolist() == want.tolist()
    assert stats["n_windows"] >= 2
    assert stats["n_batches"] >= stats["n_windows"]
    assert stats["staged_bytes"] >= stats["volume_bytes"]


@pytest.mark.parametrize("coder_name", ["numpy", "jax", "pallas"])
def test_rebuild_device_sink_digest(tmp_path, coder_name):
    # the reconstruction digest sink must reproduce the byte sums of the
    # real shard files for the victim ids WITHOUT writing anything
    build_volume(tmp_path)
    coder = ec.get_coder(coder_name, 10, 4)
    base = os.path.join(str(tmp_path), "1")
    pipeline.stream_encode(base, coder, GEO, batch_size=4096)
    victims = [0, 3, 7, 12]
    want = pipeline.shard_file_digest(base, victims)
    stats = {}
    got = pipeline.stream_rebuild_device_sink(
        base, coder, victims, GEO, batch_size=4096, stats=stats)
    assert got.tolist() == want.tolist()
    assert stats["n_batches"] >= 1
    # no shard file was touched
    assert sorted(os.listdir(tmp_path))  # files all still present
    for i in victims:
        assert os.path.exists(base + ec.to_ext(i))


def test_rebuild_device_sink_too_few_survivors(tmp_path):
    build_volume(tmp_path)
    coder = ec.get_coder("numpy", 10, 4)
    base = os.path.join(str(tmp_path), "1")
    pipeline.stream_encode(base, coder, GEO, batch_size=4096)
    for i in range(5):
        os.remove(base + ec.to_ext(i))
    with pytest.raises(ValueError):
        pipeline.stream_rebuild_device_sink(base, coder, [5, 6], GEO)


def test_ec_layout_marker(tmp_path, caplog):
    """Both encode paths stamp .ecm; a marker with a stale version is
    refused; an unmarked set in the ambiguity window (shard size a whole
    number of large blocks) warns loudly but keeps serving — sidecars
    legitimately go missing (remote serving, copies), and every healthy
    L-large-row volume has that size too."""
    import json
    import logging

    build_volume(tmp_path)
    coder = ec.get_coder("numpy", 10, 4)
    base = os.path.join(str(tmp_path), "1")
    pipeline.stream_encode(base, coder, GEO, batch_size=4096)
    ec.write_sorted_ecx_from_idx(base)
    meta = json.load(open(base + ".ecm"))
    assert meta["layout_version"] == 2

    ev = ec.EcVolume(str(tmp_path), "", 1, GEO, coder=coder)
    for sid in range(14):
        ev.add_shard(sid)
    ev.read_needle(1)  # marked: serves fine
    ev.close()

    # stale layout version: hard refusal
    json.dump({"layout_version": 1}, open(base + ".ecm", "w"))
    ev = ec.EcVolume(str(tmp_path), "", 1, GEO, coder=coder)
    for sid in range(14):
        ev.add_shard(sid)
    with pytest.raises(IOError, match="layout version"):
        ev.read_needle(1)
    ev.close()

    # unmarked + ambiguous size: warning, not refusal
    os.remove(base + ".ecm")
    sz = os.path.getsize(base + ec.to_ext(0))
    pad = (-sz) % GEO.large_block_size or GEO.large_block_size
    for i in range(14):
        with open(base + ec.to_ext(i), "ab") as f:
            f.write(bytes(pad))
    ev = ec.EcVolume(str(tmp_path), "", 1, GEO, coder=coder)
    for sid in range(14):
        ev.add_shard(sid)
    with caplog.at_level(logging.WARNING, logger="ec"):
        try:
            ev.read_needle(1)
        except Exception:
            pass  # the padded layout really is misaddressed — the point
            # here is that the warning fired before any read was served
    assert any("unmarked EC shard set" in r.message for r in caplog.records)
    ev.close()


def test_stream_rebuild_too_few_shards(tmp_path):
    build_volume(tmp_path)
    coder = ec.get_coder("numpy", 10, 4)
    base = os.path.join(str(tmp_path), "1")
    pipeline.stream_encode(base, coder, GEO, batch_size=4096)
    for i in range(5):
        os.remove(base + ec.to_ext(i))
    with pytest.raises(ValueError):
        pipeline.stream_rebuild(base, coder, GEO)


def test_reader_error_propagates(tmp_path):
    # a truncated survivor shard must raise, not hang the pipeline
    build_volume(tmp_path)
    coder = ec.get_coder("numpy", 10, 4)
    base = os.path.join(str(tmp_path), "1")
    pipeline.stream_encode(base, coder, GEO)
    os.remove(base + ec.to_ext(2))
    with open(base + ec.to_ext(3), "r+b") as f:
        f.truncate(os.path.getsize(base + ec.to_ext(3)) - 37)
    with pytest.raises(IOError):
        pipeline.stream_rebuild(base, coder, GEO, batch_size=4096)
