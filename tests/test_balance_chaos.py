"""Live mini-cluster chaos for the auto-balancer (the ISSUE acceptance
drills): a heat-skewed node drains through the real copy->verify->retire
move path with ZERO acked-read/write loss while the
``master.balance.move`` fault kills the first attempt at the worst
moment, and a crash between copy and retire leaves a complete copy on
at least one side (here: both) that the next pass converges to exactly
one.

Heat is REAL end to end: client downloads bump the volume server's
HeatTracker, heartbeats drain the deltas, the master's topology merges
them, and the balancer daemon — running on its timer, not poked by the
test — plans from that view.
"""

import os
import threading
import time

import pytest

from cluster_util import Cluster
from seaweedfs_tpu import faults
from seaweedfs_tpu.balance import BalanceConfig


def _wait(predicate, timeout=40.0, what=""):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if predicate():
            return
        time.sleep(0.05)
    raise AssertionError(f"timeout waiting for {what}")


@pytest.fixture()
def fast_heat():
    """Shrink the volume servers' heat EWMA window so a burst of reads
    ranks a node hot within a couple of heartbeats."""
    old = os.environ.get("WEED_LIFECYCLE_HEAT_HALFLIFE")
    os.environ["WEED_LIFECYCLE_HEAT_HALFLIFE"] = "2"
    faults.clear()
    yield
    faults.clear()
    if old is None:
        os.environ.pop("WEED_LIFECYCLE_HEAT_HALFLIFE", None)
    else:
        os.environ["WEED_LIFECYCLE_HEAT_HALFLIFE"] = old


def _balance_cluster(interval: float) -> Cluster:
    return Cluster(
        n_volume_servers=3, pulse=0.15,
        master_kwargs={"balance_config": BalanceConfig(
            interval=interval, cooldown=3.0, max_moves=2,
            min_rate=0.01, watermark=1.0, force_enabled=True)})


def _sealed_pair(c: Cluster):
    """Upload ~0.95MB blobs (volume limit is 1MB: each seals its
    volume by size) into distinct collections — one volume each — until
    one server holds two of them; a single hot volume would (correctly)
    never move, the strict-improvement guard refuses to relocate a lone
    hotspot.  Returns (node_url, [(vid, fid, data), (vid, fid, data)])."""
    held: dict[str, list] = {}
    for i in range(8):
        data = bytes([65 + i]) * 972_800
        fid = c.client.upload(data, collection=f"hot{i}")
        vid = int(fid.split(",")[0])
        c.wait_heartbeats()
        holder = next(vs.url for vs in c.volume_servers
                      if vs.store.find_volume(vid) is not None)
        held.setdefault(holder, []).append((vid, fid, data))
        if len(held[holder]) >= 2:
            return holder, held[holder][:2]
    raise AssertionError(f"no server ever held two volumes: {held}")


class _Reader(threading.Thread):
    """Hammers both hot blobs for the whole test: the heat source AND
    the zero-acked-read-loss probe.  Every successful read must return
    the exact bytes; transient lookup races during the move window are
    tolerated but counted."""

    def __init__(self, c: Cluster, blobs):
        super().__init__(daemon=True)
        self.c, self.blobs = c, blobs
        self.stop = threading.Event()
        self.ok = 0
        self.transient = 0
        self.corrupt = 0

    def run(self):
        while not self.stop.is_set():
            for vid, fid, data in self.blobs:
                self.c.client._vid_cache.clear()
                try:
                    got = self.c.client.download(fid)
                except Exception:
                    self.transient += 1
                    continue
                if got == data:
                    self.ok += 1
                else:
                    self.corrupt += 1
            time.sleep(0.01)


def test_hot_node_drains_zero_loss_through_injected_move_kill(fast_heat):
    """The headline acceptance: reads heat one node, the balancer
    drains it; the FIRST move attempt dies on the master.balance.move
    fault (fired before the copy — the worst-case kill window) leaving
    the source complete; the retry converges; no read ever returned
    wrong bytes and every acked write stays readable."""
    c = _balance_cluster(interval=0.25)
    try:
        leader = c.master
        src_url, blobs = _sealed_pair(c)
        # worst-case kill: the first move dies before its copy starts
        faults.set_fault("master.balance.move", "error", count=1)

        reader = _Reader(c, blobs)
        reader.start()
        writer_fids = []
        try:
            _wait(lambda: leader.balancer.recent
                  and any(e["outcome"] == "failed"
                          for e in leader.balancer.recent),
                  timeout=45, what="injected move failure")
            failed = next(e for e in leader.balancer.recent
                          if e["outcome"] == "failed")
            assert "master.balance.move" in failed["error"]
            # the killed move destroyed nothing: the source still
            # holds both volumes (the reader is proving it continuously)
            src_vs = next(vs for vs in c.volume_servers
                          if vs.url == src_url)
            for vid, _, _ in blobs:
                assert src_vs.store.find_volume(vid) is not None

            # acked writes during the move window must never be lost
            for i in range(3):
                writer_fids.append(
                    (c.client.upload(b"w%d" % i * 64), b"w%d" % i * 64))

            _wait(lambda: leader.balancer.moves_done >= 1, timeout=60,
                  what="retried move to complete")
        finally:
            reader.stop.set()
            reader.join(timeout=10)

        moved = next(e for e in leader.balancer.recent
                     if e["outcome"] == "ok")
        assert moved["src"] == src_url
        vid = moved["volume"]
        c.wait_heartbeats()
        # exactly one complete copy, on the destination
        holders = [vs.url for vs in c.volume_servers
                   if vs.store.find_volume(vid) is not None]
        assert holders == [moved["dst"]], holders
        # zero acked-read loss: plenty of reads landed, none corrupt,
        # and both blobs read back exactly after the move
        assert reader.ok > 0 and reader.corrupt == 0, vars(reader)
        for _, fid, data in blobs:
            c.client._vid_cache.clear()
            assert c.client.download(fid) == data
        for fid, data in writer_fids:
            assert c.client.download(fid) == data
    finally:
        faults.clear()
        c.shutdown()


def test_crash_between_copy_and_retire_leaves_complete_copy(fast_heat):
    """Kill the move AFTER the copy verified but BEFORE the source
    retires (a daemon crash in the other half of the window): both
    sides hold a complete copy — never neither — and the retry's
    resume path (_dst_has_volume short-circuit) retires the source
    without re-copying."""
    c = _balance_cluster(interval=0.3)
    try:
        leader = c.master
        src_url, blobs = _sealed_pair(c)

        copies, crashed = [], []
        orig = leader._admin_post

        async def flaky(url, op, body, timeout=60.0):
            if op == "volume/copy":
                copies.append(url)
            if op == "volume/delete" and not crashed:
                crashed.append(url)
                raise RuntimeError("injected crash before retire")
            return await orig(url, op, body, timeout=timeout)

        leader._admin_post = flaky
        reader = _Reader(c, blobs)
        reader.start()
        try:
            _wait(lambda: crashed, timeout=45,
                  what="move to crash between copy and retire")
            # the window the invariant is about: copy landed, retire
            # didn't — BOTH sides complete, reads keep flowing
            failed = next(e for e in leader.balancer.recent
                          if e["outcome"] == "failed")
            vid = failed["volume"]
            holders = [vs.url for vs in c.volume_servers
                       if vs.store.find_volume(vid) is not None]
            assert len(holders) == 2 and src_url in holders, holders

            _wait(lambda: leader.balancer.moves_done >= 1, timeout=60,
                  what="resume path to retire the source")
        finally:
            reader.stop.set()
            reader.join(timeout=10)

        moved = next(e for e in leader.balancer.recent
                     if e["outcome"] == "ok")
        assert moved["volume"] == vid
        # resume path: the retry never re-copied (one copy total)
        assert len(copies) == 1, copies
        c.wait_heartbeats()
        holders = [vs.url for vs in c.volume_servers
                   if vs.store.find_volume(vid) is not None]
        assert holders == [moved["dst"]], holders
        assert reader.corrupt == 0 and reader.ok > 0, vars(reader)
        for _, fid, data in blobs:
            c.client._vid_cache.clear()
            assert c.client.download(fid) == data
    finally:
        c.shutdown()
