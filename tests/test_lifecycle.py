"""Lifecycle plane: heat tracking, policy, and chaos e2e.

Covers the PR acceptance end to end on live mini-clusters:
* an idle sealed volume is vacuumed and EC-encoded to 14/14 shards with
  ZERO operator commands, shards byte-identical to a manual encode of
  the same volume;
* a crash injected mid-transition (fault plane) leaves the volume
  readable — original or reconstructed — and the daemon converges on
  retry with backoff;
* TTL collection expiry frees disk and drops the volume from topology;
* S3 bucket Expiration deletes aged objects and Transition(WARM) moves
  them to the warm tier, both visible in lifecycle_transitions metrics
  and lifecycle.status;
* heartbeats stay O(changed volumes) — idle nodes report no heat.
"""

import json
import os
import random
import shutil
import time
import urllib.error
import urllib.request

import pytest

from cluster_util import TEST_GEOMETRY, Cluster, free_port
from seaweedfs_tpu import faults
from seaweedfs_tpu.lifecycle import (HeatTracker, LifecycleConfig,
                                     plan_transitions)
from seaweedfs_tpu.lifecycle.heat import VolumeHeat
from seaweedfs_tpu.shell.ec_commands import EcCommands

TOTAL = TEST_GEOMETRY.total_shards  # 14, matching production RS(10,4)


def _wait(predicate, timeout=40.0, what=""):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if predicate():
            return
        time.sleep(0.1)
    raise AssertionError(f"timeout waiting for {what}")


def _shard_count(c, vid) -> int:
    try:
        return len(c.client.ec_lookup(vid).get("shards", {}))
    except Exception:
        return 0


def _leader(c):
    return next(m for m in c.masters if m.raft.is_leader)


def _master_json(c, path):
    with urllib.request.urlopen(
            f"http://{_leader(c).url}{path}", timeout=10) as r:
        return json.load(r)


def _metric_lines(c, name):
    with urllib.request.urlopen(f"http://{_leader(c).url}/metrics",
                                timeout=10) as r:
        text = r.read().decode()
    return [ln for ln in text.splitlines() if ln.startswith(name)]


def _fill_volume(c, collection, target_bytes=None, blob=64 * 1024,
                 seed=21):
    """Upload random (incompressible) blobs until one volume of the
    collection crosses ~target_bytes; returns (vid, {fid: data})."""
    target = target_bytes or int(0.95 * 1024 * 1024)
    rng = random.Random(seed)
    blobs = {}
    for _ in range(64):
        data = bytes(rng.getrandbits(8) for _ in range(blob))
        fid = c.client.upload(data, collection=collection)
        blobs[fid] = data
        sizes = {}
        for nd in c.client.dir_status().get("nodes", []):
            for v in nd.get("volumes", []):
                if v.get("collection") == collection:
                    sizes[v["id"]] = max(sizes.get(v["id"], 0), v["size"])
        full = [vid for vid, s in sizes.items() if s >= target]
        if full:
            vid = full[0]
            return vid, {f: d for f, d in blobs.items()
                         if int(f.split(",")[0]) == vid}
        c.wait_heartbeats()
    raise AssertionError("no volume filled")


# --- unit: heat tracker ---

def test_heat_tracker_deltas_are_changed_volumes_only():
    t = HeatTracker(halflife=10.0)
    for _ in range(5):
        t.record_read(1)
    t.record_write(2)
    out = t.deltas()
    assert sorted(e["id"] for e in out) == [1, 2]
    one = next(e for e in out if e["id"] == 1)
    assert one["reads"] == 5 and one["writes"] == 0
    assert one["last_access"] > 0 and one["read_rate"] > 0
    # nothing touched since the drain -> empty delta, not a re-send
    assert t.deltas() == []
    t.record_read(1)
    assert [e["id"] for e in t.deltas()] == [1]


def test_heat_tracker_prunes_departed_volumes():
    t = HeatTracker()
    t.record_read(7)
    t.record_read(8)
    out = t.deltas(known_vids={7})
    assert [e["id"] for e in out] == [7]
    assert 8 not in t._stats


def test_volume_heat_merge_and_decay():
    vh = VolumeHeat(first_seen=100.0, updated=100.0)
    vh.merge({"reads": 10, "writes": 2, "last_access": 105.0,
              "read_rate": 4.0}, now=105.0)
    assert vh.reads == 10 and vh.writes == 2
    assert vh.rate_now(105.0) == pytest.approx(4.0)
    # one half-life later the remembered rate halves
    assert vh.rate_now(105.0 + 600.0) == pytest.approx(2.0, rel=1e-3)


# --- unit: policy planning (pure, no cluster) ---

class _FakeTopo:
    def __init__(self, volume_size_limit=1024 * 1024):
        from seaweedfs_tpu.topology.topology import DataNode
        self.volume_size_limit = volume_size_limit
        self.nodes = {}
        self.layouts = {}

    def add(self, url, volumes=(), ec=()):
        from seaweedfs_tpu.topology.topology import (DataNode, EcShardInfo,
                                                     VolumeInfo)
        n = DataNode(url, url, url, "dc", "r", 16)
        for v in volumes:
            n.volumes[v["id"]] = VolumeInfo.from_dict(v)
        for s in ec:
            n.ec_shards[s["id"]] = EcShardInfo.from_dict(s)
        self.nodes[url] = n
        return n


def test_policy_warm_requires_full_and_idle():
    topo = _FakeTopo()
    topo.add("a:1", volumes=[
        {"id": 1, "size": 1000_000, "last_modified": 50},   # full
        {"id": 2, "size": 10_000, "last_modified": 50},     # small
    ])
    cfg = LifecycleConfig(warm_after=60.0)
    heat = {1: {"last_access": 100.0, "first_seen": 0.0},
            2: {"last_access": 100.0, "first_seen": 0.0}}
    # idle long enough: only the full volume goes warm
    plan = plan_transitions(topo, heat, cfg, now=200.0)
    assert [(t.kind, t.vid) for t in plan] == [("warm", 1)]
    # recently accessed: nothing goes warm
    assert plan_transitions(topo, heat, cfg, now=120.0) == []
    # a fresh master with no access history waits from first_seen
    heat_fresh = {1: {"last_access": 0.0, "first_seen": 190.0}}
    assert plan_transitions(topo, heat_fresh, cfg, now=200.0) == []


def test_policy_s3_nudge_overrides_idleness():
    topo = _FakeTopo()
    topo.add("a:1", volumes=[{"id": 3, "size": 10_000}])
    cfg = LifecycleConfig(warm_after=3600.0)
    heat = {3: {"last_access": 199.0, "first_seen": 0.0}}
    plan = plan_transitions(topo, heat, cfg, now=200.0,
                            warm_requested={3: "s3 transition b/*"})
    assert [(t.kind, t.vid) for t in plan] == [("warm", 3)]


def test_policy_unec_on_hot_read_rate():
    topo = _FakeTopo()
    topo.add("a:1", ec=[{"id": 4, "shard_ids": list(range(14))}])
    cfg = LifecycleConfig(hot_read_rate=2.0)
    assert plan_transitions(topo, {4: {"read_rate": 1.0}}, cfg, 100.0) == []
    plan = plan_transitions(topo, {4: {"read_rate": 2.5}}, cfg, 100.0)
    assert [(t.kind, t.vid) for t in plan] == [("unec", 4)]


def test_policy_expiry_volume_ttl_and_collection_rules():
    topo = _FakeTopo()
    topo.add("a:1", volumes=[
        {"id": 5, "collection": "tmp", "last_modified": 100},
        {"id": 6, "collection": "keep", "last_modified": 100},
        {"id": 7, "ttl": "1m", "last_modified": 100},
    ])
    cfg = LifecycleConfig(collection_ttls={"tmp": 30.0}, ttl_grace=0.0)
    heat = {}
    plan = plan_transitions(topo, heat, cfg, now=200.0)
    kinds = {(t.kind, t.vid) for t in plan}
    assert ("expire", 5) in kinds          # collection rule: 30s elapsed
    assert all(t.vid != 6 for t in plan)   # no rule for "keep"
    assert ("expire", 7) in kinds          # superblock ttl 60s elapsed
    # ttl volumes never ALSO go warm
    assert all(t.kind == "expire" for t in plan)


def test_heat_tracker_requeue_after_failed_delivery():
    t = HeatTracker(halflife=10.0)
    t.record_read(1)
    t.record_read(1)
    t.record_write(2)
    drained = t.deltas()
    assert t.deltas() == []  # drained clean
    # a failed heartbeat puts the window back; nothing is lost
    t.requeue(drained)
    again = t.deltas()
    by_id = {e["id"]: e for e in again}
    assert by_id[1]["reads"] == 2
    assert by_id[2]["writes"] == 1
    assert by_id[1]["last_access"] == \
        pytest.approx(next(e for e in drained
                           if e["id"] == 1)["last_access"])


def test_policy_resume_requires_idleness():
    """A dual vols+ecs state is only resumed while the volume is IDLE:
    a freshly un-EC'd hot volume also shows the dual state through one
    stale-heartbeat window, and resuming there would delete the copy
    users just got back."""
    topo = _FakeTopo()
    topo.add("a:1", volumes=[{"id": 9, "size": 1000_000}],
             ec=[{"id": 9, "shard_ids": list(range(14))}])
    cfg = LifecycleConfig(warm_after=60.0)
    # hot (recent access): the dual state is left alone
    heat_hot = {9: {"last_access": 195.0, "first_seen": 0.0}}
    assert plan_transitions(topo, heat_hot, cfg, now=200.0) == []
    # idle: a crashed warm transition — resume it
    heat_idle = {9: {"last_access": 100.0, "first_seen": 0.0}}
    plan = plan_transitions(topo, heat_idle, cfg, now=200.0)
    assert [(t.kind, t.vid) for t in plan] == [("warm", 9)]
    assert "resume" in plan[0].reason


def test_policy_expiry_covers_warm_tier():
    """A collection TTL added after data was tiered still expires it —
    and an expiring EC volume never also decodes back to hot."""
    topo = _FakeTopo()
    topo.add("a:1", ec=[{"id": 11, "collection": "logs",
                         "shard_ids": list(range(14))}])
    cfg = LifecycleConfig(collection_ttls={"logs": 30.0}, ttl_grace=0.0,
                          hot_read_rate=1.0)
    heat = {11: {"last_access": 100.0, "first_seen": 50.0,
                 "read_rate": 5.0}}  # hot AND expired: expiry wins
    plan = plan_transitions(topo, heat, cfg, now=200.0)
    assert [(t.kind, t.vid) for t in plan] == [("expire", 11)]
    # not yet elapsed -> untouched (and unec may fire normally)
    plan = plan_transitions(topo, heat, cfg, now=120.0)
    assert [(t.kind, t.vid) for t in plan] == [("unec", 11)]


# --- satellite: heartbeat payload stays O(changed volumes) ---

def test_heartbeat_heat_payload_is_delta_sized():
    c = Cluster(n_volume_servers=1)
    try:
        vs = c.volume_servers[0]
        # seed a couple of volumes, then FREEZE the heartbeat loop so
        # this test (not the 0.15s pulse) controls when deltas drain
        warm_fid = c.client.upload(b"x" * 500)

        async def _halt():
            vs._hb_task.cancel()

        c.call(_halt())
        time.sleep(0.1)
        vs.heat.deltas()  # drain whatever the live loop left behind

        # an idle beat carries NO heat entries at all, no matter how
        # many volumes the node holds
        idle = vs._hb_payload()
        assert "heat" not in idle
        # one read -> exactly one changed entry, for exactly that vid
        c.client.download(warm_fid)
        one = vs._hb_payload()
        assert [e["id"] for e in one.get("heat", [])] == \
            [int(warm_fid.split(",")[0])]
        entry = one["heat"][0]
        assert entry["reads"] == 1 and entry["last_access"] > 0
        # drained again -> back to zero-size
        assert "heat" not in vs._hb_payload()
    finally:
        c.shutdown()


# --- satellite: gRPC-heartbeat nodes deliver heat via the side channel
#     (the pb schema has no heat field) ---

def test_grpc_heartbeat_heat_rides_the_side_channel():
    from cluster_util import free_port as _fp
    grpc_port = _fp()
    c = Cluster(n_volume_servers=0, master_grpc_port=grpc_port)
    try:
        c.add_volume_server(use_grpc_heartbeat=True)
        c.wait_for_nodes(1)
        fid = c.client.upload(b"grpc-heat" * 64)
        vid = int(fid.split(",")[0])
        c.client.download(fid)

        def heat_arrived():
            h = c.masters[0].topology.heat_view()
            return h.get(vid, {}).get("reads", 0) >= 1
        _wait(heat_arrived, timeout=15,
              what="heat deltas via /vol/heat/report on a gRPC-"
                   "heartbeat node")
    finally:
        c.shutdown()


# --- e2e: idle sealed volume -> auto EC, byte-identical shards ---

def test_idle_volume_auto_ec_time_driven(tmp_path):
    cfg = LifecycleConfig(warm_after=1.0, interval=0.3,
                          full_fraction=0.9)
    c = Cluster(n_volume_servers=4,
                master_kwargs={"lifecycle_config": cfg})
    try:
        vid, blobs = _fill_volume(c, "warmtest")
        assert blobs, "filled volume must hold test data"
        c.wait_heartbeats()
        # snapshot the sealed volume BEFORE the daemon touches it (it
        # can't fire for another warm_after second) for the manual
        # reference encode
        holder = next(vs for vs in c.volume_servers
                      if vs.store.find_volume(vid) is not None)
        base = holder.store.find_volume(vid).base_file_name()
        ref_base = os.path.join(str(tmp_path), f"warmtest_{vid}")
        shutil.copy(base + ".dat", ref_base + ".dat")
        shutil.copy(base + ".idx", ref_base + ".idx")

        # ZERO operator commands from here: the daemon seals, vacuums,
        # encodes through the governed feed, spreads, and retires
        _wait(lambda: _shard_count(c, vid) == TOTAL, timeout=45,
              what="time-driven auto ec.encode to 14/14")
        _wait(lambda: not any(vs.store.find_volume(vid) is not None
                              for vs in c.volume_servers),
              timeout=30, what="original volume retired everywhere")

        # shards byte-identical to a manual one-pass warm-down of the
        # same snapshot (the daemon's warm path is fused by default:
        # compact+gzip+encode in one pass, so the reference must be the
        # fused transform of the sealed volume, not a plain encode)
        from seaweedfs_tpu import ec as ec_mod
        from seaweedfs_tpu.ec import fused as ec_fused
        from seaweedfs_tpu.storage.volume import Volume as _Vol
        coder = ec_mod.get_coder("numpy", TEST_GEOMETRY.data_shards,
                                 TEST_GEOMETRY.parity_shards)
        ref_v = _Vol(str(tmp_path), "warmtest", vid)
        ref_out = ref_base + ".warm"
        ec_fused.fused_vacuum_gzip_encode(ref_v, ref_out, coder,
                                          TEST_GEOMETRY)
        ref_v.close()
        for sid in range(TOTAL):
            ext = ec_mod.to_ext(sid)
            live = None
            for vs in c.volume_servers:
                for loc in vs.store.locations:
                    p = os.path.join(loc.directory, f"warmtest_{vid}{ext}")
                    if os.path.exists(p):
                        live = p
                        break
                if live:
                    break
            assert live is not None, f"shard {sid} file not found"
            with open(live, "rb") as a, open(ref_out + ext, "rb") as b:
                assert a.read() == b.read(), \
                    f"shard {sid} differs from the manual warm-down"

        # the data is intact through the warm tier
        c.client._vid_cache.clear()
        for fid, data in blobs.items():
            assert c.client.download(fid) == data

        # observable: metrics + lifecycle.status + volume.heat state
        lines = _metric_lines(
            c, "seaweedfs_tpu_master_lifecycle_transitions_total")
        assert any('kind="warm"' in ln and 'outcome="ok"' in ln
                   for ln in lines), lines
        status = _master_json(c, "/lifecycle/status")
        assert any(e["kind"] == "warm" and e["outcome"] == "ok"
                   and e["volume"] == vid for e in status["recent"])
        heat = _master_json(c, f"/vol/heat?volumeId={vid}")
        assert heat["volumes"] and heat["volumes"][0]["state"] == "warm"
    finally:
        c.shutdown()


# --- chaos: crash mid-transition -> no data loss, converges on retry ---

def test_crash_mid_transition_keeps_data_and_converges():
    faults.clear()
    cfg = LifecycleConfig(warm_after=0.8, interval=0.3)
    c = Cluster(n_volume_servers=4,
                master_kwargs={"lifecycle_config": cfg})
    try:
        # the worst moment: full shard set mounted, original not yet
        # retired — the injected error kills the transition right there
        faults.set_fault("lifecycle.encode", "error")
        vid, blobs = _fill_volume(c, "chaos", seed=31)
        c.wait_heartbeats()

        def failed_attempts():
            st = _master_json(c, "/lifecycle/status")
            return [e for e in st["recent"]
                    if e["kind"] == "warm" and e["outcome"] == "failed"]

        _wait(lambda: failed_attempts(), timeout=40,
              what="transition to fail at the injected crash point")
        # invariant: the original volume is STILL readable mid-wreckage
        c.client._vid_cache.clear()
        for fid, data in blobs.items():
            assert c.client.download(fid) == data
        assert any(vs.store.find_volume(vid) is not None
                   for vs in c.volume_servers), \
            "original must survive a crash before retirement"
        # the daemon retries with backoff, not a hot loop: give it time
        # to fail at least twice, then check the failure count is small
        _wait(lambda: len(failed_attempts()) >= 2, timeout=40,
              what="a backed-off retry")
        t0 = time.time()
        n0 = len(failed_attempts())
        time.sleep(2.0)
        assert len(failed_attempts()) - n0 <= 4, \
            "retries must back off, not spin"

        # clear the fault: the next retry converges to 14/14 and the
        # original is retired
        faults.clear()
        _wait(lambda: _shard_count(c, vid) == TOTAL, timeout=60,
              what="convergence to 14/14 after the fault clears")
        _wait(lambda: not any(vs.store.find_volume(vid) is not None
                              for vs in c.volume_servers),
              timeout=40, what="original retired after convergence")
        c.client._vid_cache.clear()
        for fid, data in blobs.items():
            assert c.client.download(fid) == data
        lines = _metric_lines(
            c, "seaweedfs_tpu_master_lifecycle_transitions_total")
        assert any('kind="warm"' in ln and 'outcome="failed"' in ln
                   for ln in lines)
        assert any('kind="warm"' in ln and 'outcome="ok"' in ln
                   for ln in lines)
    finally:
        faults.clear()
        c.shutdown()


# --- e2e: TTL collection expiry frees disk + drops from topology ---

def test_ttl_collection_expiry_frees_disk_and_topology():
    cfg = LifecycleConfig(collection_ttls={"tmp": 1.0}, ttl_grace=0.0,
                          interval=0.3)
    c = Cluster(n_volume_servers=2,
                master_kwargs={"lifecycle_config": cfg})
    try:
        fid = c.client.upload(b"ephemeral" * 100, collection="tmp")
        vid = int(fid.split(",")[0])
        c.wait_heartbeats()
        assert c.client.lookup(vid)
        dat_files = [os.path.join(loc.directory, f"tmp_{vid}.dat")
                     for vs in c.volume_servers
                     for loc in vs.store.locations]
        assert any(os.path.exists(p) for p in dat_files)

        def gone_from_topology():
            try:
                c.client._vid_cache.clear()
                return not c.client.lookup(vid)
            except Exception:
                return True

        _wait(gone_from_topology, timeout=30,
              what="expired volume dropped from topology")
        # disk actually freed, on every holder, whole volume at once
        _wait(lambda: not any(os.path.exists(p) for p in dat_files),
              timeout=20, what="volume files removed from disk")
        st = _master_json(c, "/lifecycle/status")
        assert any(e["kind"] == "expire" and e["outcome"] == "ok"
                   and e["volume"] == vid for e in st["recent"])
    finally:
        c.shutdown()


# --- e2e: warm -> hot (un-EC when the read rate crosses the bar) ---

def test_hot_ec_volume_is_decoded_back():
    os.environ["WEED_LIFECYCLE_HEAT_HALFLIFE"] = "0.5"
    cfg = LifecycleConfig(hot_read_rate=1.0, interval=0.3)
    c = Cluster(n_volume_servers=4,
                master_kwargs={"lifecycle_config": cfg})
    try:
        rng = random.Random(41)
        data = bytes(rng.getrandbits(8) for _ in range(50_000))
        fid = c.client.upload(data, collection="hotset")
        vid = int(fid.split(",")[0])
        c.wait_heartbeats()
        EcCommands(c.client, TEST_GEOMETRY).encode(vid, "hotset",
                                                   apply=True)
        c.wait_heartbeats()
        assert _shard_count(c, vid) == TOTAL

        from seaweedfs_tpu.client import ClientError

        def hammer_and_decoded():
            c.client._vid_cache.clear()
            for _ in range(40):
                try:
                    assert c.client.download(fid) == data
                except ClientError:
                    # mid-decode window: a just-deleted shard set can
                    # answer 404 until the next heartbeat lands; the
                    # post-decode read below proves no data was lost
                    break
            try:
                return bool(c.client.lookup(vid))
            except Exception:
                return False

        _wait(hammer_and_decoded, timeout=45,
              what="hot EC volume decoded back to a normal volume")
        _wait(lambda: _shard_count(c, vid) == 0, timeout=30,
              what="shards dropped after the decode")

        def readable():
            c.client._vid_cache.clear()
            try:
                return c.client.download(fid) == data
            except ClientError:
                return False

        _wait(readable, timeout=20, what="data intact after the decode")
        st = _master_json(c, "/lifecycle/status")
        assert any(e["kind"] == "unec" and e["outcome"] == "ok"
                   for e in st["recent"])
    finally:
        os.environ.pop("WEED_LIFECYCLE_HEAT_HALFLIFE", None)
        c.shutdown()


# --- e2e: S3 lifecycle configuration, enforced by the same daemon ---

def _s3_req(port, method, path, body=None, headers=None):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=body, method=method,
        headers=headers or {})
    try:
        with urllib.request.urlopen(req, timeout=15) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def test_s3_lifecycle_rules_end_to_end():
    cfg = LifecycleConfig(interval=0.3, day_seconds=1.0,
                          force_enabled=True)
    c = Cluster(n_volume_servers=4,
                master_kwargs={"lifecycle_config": cfg})
    try:
        filer = c.add_filer()
        # the daemon learns the filer after boot (tests wire it late)
        _leader(c).lifecycle.cfg.filer = filer.url
        from seaweedfs_tpu.s3.s3_server import S3Server
        s3 = S3Server(filer.url)
        port = free_port()
        c.serve(s3.app, port)

        assert _s3_req(port, "PUT", "/b1")[0] == 200
        # no configuration yet -> NoSuchLifecycleConfiguration
        code, body = _s3_req(port, "GET", "/b1?lifecycle")
        assert code == 404 and b"NoSuchLifecycleConfiguration" in body
        # malformed / unsupported XML is rejected, not silently accepted
        bad = (b"<LifecycleConfiguration><Rule><Status>Enabled</Status>"
               b"<Transition><Days>1</Days><StorageClass>GLACIER"
               b"</StorageClass></Transition></Rule>"
               b"</LifecycleConfiguration>")
        assert _s3_req(port, "PUT", "/b1?lifecycle", bad)[0] == 400

        rules = (b"<LifecycleConfiguration>"
                 b"<Rule><ID>old</ID><Filter><Prefix>old/</Prefix></Filter>"
                 b"<Status>Enabled</Status>"
                 b"<Expiration><Days>1</Days></Expiration></Rule>"
                 b"<Rule><ID>arc</ID><Filter><Prefix>arc/</Prefix></Filter>"
                 b"<Status>Enabled</Status>"
                 b"<Transition><Days>0</Days><StorageClass>WARM"
                 b"</StorageClass></Transition></Rule>"
                 b"</LifecycleConfiguration>")
        assert _s3_req(port, "PUT", "/b1?lifecycle", rules)[0] == 200
        code, body = _s3_req(port, "GET", "/b1?lifecycle")
        assert code == 200 and b"<Prefix>old/</Prefix>" in body \
            and b"WARM" in body

        rng = random.Random(51)
        payload = bytes(rng.getrandbits(8) for _ in range(20_000))
        assert _s3_req(port, "PUT", "/b1/old/a.bin", payload)[0] == 200
        assert _s3_req(port, "PUT", "/b1/arc/b.bin", payload)[0] == 200
        assert _s3_req(port, "PUT", "/b1/keep.bin", payload)[0] == 200

        # Expiration: with day_seconds=1 the 1-"day" rule fires after 1s
        _wait(lambda: _s3_req(port, "GET", "/b1/old/a.bin")[0] == 404,
              timeout=30, what="aged object expired by the daemon")
        # untouched keys survive
        assert _s3_req(port, "GET", "/b1/keep.bin")[1] == payload

        # Transition: the object reports WARM in listings...
        def listed_warm():
            _, body = _s3_req(port, "GET", "/b1?prefix=arc/")
            return (b"<Key>arc/b.bin</Key>" in body
                    and b"<StorageClass>WARM</StorageClass>" in body)

        _wait(listed_warm, timeout=30,
              what="transitioned object listed as WARM")

        # ...and its chunk volumes really move to the warm (EC) tier
        def chunks_warm():
            st = _master_json(c, "/lifecycle/status")
            ok_warm = [e for e in st["recent"]
                       if e["kind"] == "warm" and e["outcome"] == "ok"]
            return bool(ok_warm)

        _wait(chunks_warm, timeout=45,
              what="chunk volume EC-encoded via the transition nudge")
        # the object is still fully readable from the warm tier
        assert _s3_req(port, "GET", "/b1/arc/b.bin")[1] == payload

        lines = _metric_lines(
            c, "seaweedfs_tpu_master_lifecycle_transitions_total")
        assert any('kind="s3_expire"' in ln for ln in lines), lines
        assert any('kind="s3_transition"' in ln for ln in lines), lines
        st = _master_json(c, "/lifecycle/status")
        kinds = {e["kind"] for e in st["recent"]}
        assert {"s3_expire", "s3_transition"} <= kinds

        # DeleteBucketLifecycle stops enforcement
        assert _s3_req(port, "DELETE", "/b1?lifecycle")[0] == 204
        assert _s3_req(port, "GET", "/b1?lifecycle")[0] == 404
    finally:
        c.shutdown()


def test_encode_batcher_coalesces_same_source():
    """Concurrent warm transitions sharing a source must encode as ONE
    multi-volume ec/generate window; distinct sources stay separate."""
    import asyncio

    from seaweedfs_tpu.lifecycle.daemon import _EncodeBatcher

    calls = []

    class FakeMaster:
        async def _admin_post(self, url, path, body, timeout=None):
            calls.append((url, path, body))

    class FakeDaemon:
        master = FakeMaster()
        _tasks: set = set()

    async def run():
        b = _EncodeBatcher(FakeDaemon(), linger=0.05)
        await asyncio.gather(b.encode("v1:8080", 1),
                             b.encode("v1:8080", 2),
                             b.encode("v2:8080", 3))

    asyncio.run(run())
    v1 = [c for c in calls if c[0] == "v1:8080"]
    assert len(v1) == 1, calls
    assert sorted(v1[0][2]["volume_ids"]) == [1, 2]
    v2 = [c for c in calls if c[0] == "v2:8080"]
    assert len(v2) == 1 and v2[0][2] == {"volume_id": 3}


def test_encode_batcher_window_cap_flushes_immediately(monkeypatch):
    import asyncio

    from seaweedfs_tpu.lifecycle import daemon as daemon_mod

    monkeypatch.setenv("WEED_EC_ENCODE_WINDOW", "2")
    calls = []

    class FakeMaster:
        async def _admin_post(self, url, path, body, timeout=None):
            calls.append(body)

    class FakeDaemon:
        master = FakeMaster()
        _tasks: set = set()

    async def run():
        b = daemon_mod._EncodeBatcher(FakeDaemon(), linger=5.0)
        # linger is far longer than the test: only the window cap can
        # flush, proving a full window never waits out the linger
        await asyncio.wait_for(
            asyncio.gather(b.encode("v1:8080", 1), b.encode("v1:8080", 2)),
            timeout=2.0)

    asyncio.run(run())
    assert calls and sorted(calls[0]["volume_ids"]) == [1, 2]


def test_encode_batcher_propagates_failure():
    import asyncio

    from seaweedfs_tpu.lifecycle.daemon import _EncodeBatcher

    class FakeMaster:
        async def _admin_post(self, url, path, body, timeout=None):
            raise RuntimeError("generate blew up")

    class FakeDaemon:
        master = FakeMaster()
        _tasks: set = set()

    async def run():
        b = _EncodeBatcher(FakeDaemon(), linger=0.05)
        with pytest.raises(RuntimeError, match="generate blew up"):
            await b.encode("v1:8080", 1)

    asyncio.run(run())
