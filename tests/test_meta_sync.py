"""Metadata event log, subscription, multi-filer sync, replication sinks,
and notification queues.

Mirrors the reference coverage of weed/filer/meta_aggregator.go,
weed/replication/, weed/notification/, weed/command/filer_sync.go.
"""

import json
import os
import time
import urllib.request

import pytest

from seaweedfs_tpu.filer.entry import new_directory, new_file
from seaweedfs_tpu.filer.filer import Filer, MetaEvent
from seaweedfs_tpu.filer.stores import MemoryStore
from seaweedfs_tpu.notification.queues import FileQueue, load_notifier
from seaweedfs_tpu.replication.sink import LocalSink
from seaweedfs_tpu.utils.config import Configuration


# --- event model ---

def test_meta_event_roundtrip():
    e = MetaEvent(tsns=123, directory="/d",
                  old_entry=None,
                  new_entry=new_file("/d/f", [], mime="text/plain"),
                  signatures=(7, 9))
    e2 = MetaEvent.from_dict(e.to_dict())
    assert e2.tsns == 123 and e2.directory == "/d"
    assert e2.new_entry.full_path == "/d/f"
    assert e2.signatures == (7, 9)
    assert e2.old_entry is None


def test_events_carry_own_signature():
    f = Filer(MemoryStore(), signature=42)
    f.create_entry(new_file("/a.txt", []))
    evs = f.meta_log.events_since(0)
    assert evs and evs[-1].signatures[-1] == 42


def test_meta_log_persistence(tmp_path):
    path = str(tmp_path / "meta" / "log.ndjson")
    f = Filer(MemoryStore(), meta_log_path=path, signature=1)
    f.create_entry(new_file("/x", []))
    f.create_entry(new_file("/y", []))
    f.close()
    f2 = Filer(MemoryStore(), meta_log_path=path, signature=2)
    replayed = list(f2.meta_log.read_persisted_since(0))
    assert [e.new_entry.full_path for e in replayed] == ["/x", "/y"]
    f2.close()


# --- apply_event / loop prevention ---

def test_apply_event_create_update_delete():
    a = Filer(MemoryStore(), signature=1)
    b = Filer(MemoryStore(), signature=2)
    a.meta_log.subscribe(lambda e: b.apply_event(e))

    a.create_entry(new_file("/docs/readme.md", [], mime="text/markdown"))
    got = b.find_entry("/docs/readme.md")
    assert got is not None and got.attr.mime == "text/markdown"
    assert b.find_entry("/docs").is_directory

    a.delete_entry("/docs/readme.md")
    assert b.find_entry("/docs/readme.md") is None


def test_apply_event_skips_own_signature():
    a = Filer(MemoryStore(), signature=1)
    e = MetaEvent(tsns=time.time_ns(), directory="/",
                  old_entry=None, new_entry=new_file("/z", []),
                  signatures=(5, 1))
    assert a.apply_event(e) is False
    assert a.find_entry("/z") is None


def test_two_filers_do_not_loop():
    a = Filer(MemoryStore(), signature=1)
    b = Filer(MemoryStore(), signature=2)
    # wire both directions like filer.sync; apply_event must terminate
    a.meta_log.subscribe(lambda e: b.apply_event(e))
    b.meta_log.subscribe(lambda e: a.apply_event(e))
    a.create_entry(new_file("/ping", []))
    b.create_entry(new_file("/pong", []))
    assert b.find_entry("/ping") is not None
    assert a.find_entry("/pong") is not None
    # each side's log stays bounded (no echo storm)
    assert len(a.meta_log.events_since(0)) < 10
    assert len(b.meta_log.events_since(0)) < 10


def test_apply_event_rename():
    a = Filer(MemoryStore(), signature=1)
    b = Filer(MemoryStore(), signature=2)
    a.meta_log.subscribe(lambda e: b.apply_event(e))
    a.create_entry(new_file("/old.txt", []))
    a.rename("/old.txt", "/new.txt")
    assert b.find_entry("/old.txt") is None
    assert b.find_entry("/new.txt") is not None


# --- notification queues ---

def test_file_queue_spool(tmp_path):
    q = FileQueue(str(tmp_path / "spool"))
    f = Filer(MemoryStore(), signature=3)
    f.meta_log.subscribe(q.notify)
    f.create_entry(new_file("/spooled", []))
    q.close()
    files = os.listdir(tmp_path / "spool")
    assert len(files) == 1
    lines = (tmp_path / "spool" / files[0]).read_text().splitlines()
    evs = [MetaEvent.from_dict(json.loads(l)) for l in lines]
    assert any(e.new_entry and e.new_entry.full_path == "/spooled"
               for e in evs)


def test_load_notifier_selects_first_enabled(tmp_path):
    cfg = Configuration({"notification": {
        "log": {"enabled": False},
        "file": {"enabled": True, "directory": str(tmp_path / "nq")},
    }})
    n = load_notifier(cfg)
    assert isinstance(n, FileQueue)
    assert load_notifier(Configuration({})) is None


# --- local sink ---

def test_local_sink_materializes_tree(tmp_path):
    sink = LocalSink(str(tmp_path / "out"))
    f = new_file("/a/b/c.txt", [])
    sink.create_entry(f, lambda: b"content!")
    assert (tmp_path / "out/a/b/c.txt").read_bytes() == b"content!"
    sink.create_entry(new_directory("/a/empty"), lambda: b"")
    assert (tmp_path / "out/a/empty").is_dir()
    sink.delete_entry(f)
    assert not (tmp_path / "out/a/b/c.txt").exists()


# --- gcs sink against the in-repo REST fake ---

def test_gcs_sink_contract(tmp_path):
    """GcsSink over the JSON/media REST API vs fake_gcs — create,
    overwrite, delete, 404-tolerant delete, bearer-token auth
    (gcs_sink.go:76-120)."""
    import urllib.request

    from seaweedfs_tpu.replication.fake_gcs import FakeGcsServer
    from seaweedfs_tpu.replication.sink import GcsSink

    fake = FakeGcsServer(token="tok123")
    try:
        sink = GcsSink("bkt", directory="/mirror",
                       endpoint=fake.endpoint, token="tok123")
        f = new_file("/a/b/c.txt", [])
        sink.create_entry(f, lambda: b"gcs content")
        assert fake.buckets["bkt"]["mirror/a/b/c.txt"] == b"gcs content"
        # directories are implicit: no object created
        sink.create_entry(new_directory("/a/dir"), lambda: b"")
        assert "mirror/a/dir" not in fake.buckets["bkt"]
        # overwrite
        sink.create_entry(f, lambda: b"v2")
        assert fake.buckets["bkt"]["mirror/a/b/c.txt"] == b"v2"
        # media download round-trips through the fake's GET
        with urllib.request.urlopen(
                f"{fake.endpoint}/storage/v1/b/bkt/o/"
                "mirror%2Fa%2Fb%2Fc.txt?alt=media") as r:
            assert False, "unauthenticated GET must 401"
    except urllib.error.HTTPError as e:
        assert e.code == 401
    try:
        sink.delete_entry(f)
        assert "mirror/a/b/c.txt" not in fake.buckets["bkt"]
        sink.delete_entry(f)  # idempotent: 404 swallowed
        # wrong token is rejected
        bad = GcsSink("bkt", endpoint=fake.endpoint, token="nope")
        try:
            bad.create_entry(new_file("/x", []), lambda: b"d")
            assert False, "bad token must 401"
        except urllib.error.HTTPError as e:
            assert e.code == 401
    finally:
        fake.close()


def test_gcs_sink_loads_from_config(tmp_path):
    from seaweedfs_tpu.replication.sink import GcsSink, load_sink
    from seaweedfs_tpu.utils.config import Configuration

    cfg = Configuration({"sink": {"gcs": {
        "enabled": True, "bucket": "b1", "directory": "/d",
        "endpoint": "http://127.0.0.1:1", "token": "t"}}})
    s = load_sink(cfg)
    assert isinstance(s, GcsSink)
    assert s.bucket == "b1" and s.prefix == "d"


# --- live filer servers: subscribe + sync e2e ---

@pytest.fixture(scope="module")
def cluster():
    from cluster_util import Cluster
    c = Cluster(n_volume_servers=1)
    yield c
    c.shutdown()


def test_meta_subscribe_and_sync_e2e(cluster):
    """Two filers on one blob cluster, synced via the built-in aggregator
    peers= option; writes on A appear on B and vice versa, no loops."""
    fa = cluster.add_filer()
    fb_server = None
    # filer B subscribes to A as a peer
    from cluster_util import free_port

    from seaweedfs_tpu.server.filer_server import FilerServer
    port = free_port()
    fb_server = FilerServer(cluster.master_url, store_name="memory",
                            chunk_size=16 * 1024, peers=[fa.url])
    cluster.runners.append(cluster.serve(fb_server.app, port))
    fb_server.url = f"127.0.0.1:{port}"

    def put(filer_url, path, data):
        req = urllib.request.Request(f"http://{filer_url}{path}", data=data,
                                     method="PUT")
        urllib.request.urlopen(req, timeout=30).close()

    def get(filer_url, path):
        with urllib.request.urlopen(f"http://{filer_url}{path}",
                                    timeout=30) as r:
            return r.read()

    put(fa.url, "/sync/hello.txt", b"hello from A")
    deadline = time.time() + 10
    while time.time() < deadline:
        try:
            meta = json.load(urllib.request.urlopen(
                f"http://{fb_server.url}/__meta__/lookup?path=/sync/hello.txt",
                timeout=5))
            break
        except urllib.error.HTTPError:
            time.sleep(0.1)
    else:
        raise AssertionError("entry did not sync to B")
    # chunks are shared (same blob cluster) so B can serve the data
    assert get(fb_server.url, "/sync/hello.txt") == b"hello from A"

    # delete on A propagates
    req = urllib.request.Request(f"http://{fa.url}/sync/hello.txt",
                                 method="DELETE")
    urllib.request.urlopen(req, timeout=30).close()
    deadline = time.time() + 10
    while time.time() < deadline:
        try:
            urllib.request.urlopen(
                f"http://{fb_server.url}/__meta__/lookup?path=/sync/hello.txt",
                timeout=5).close()
            time.sleep(0.1)
        except urllib.error.HTTPError:
            break
    else:
        raise AssertionError("delete did not sync to B")


def test_subscribe_stream_replays_since(cluster):
    f = cluster.add_filer()

    def put(path, data):
        req = urllib.request.Request(f"http://{f.url}{path}", data=data,
                                     method="PUT")
        urllib.request.urlopen(req, timeout=30).close()

    put("/stream/a.txt", b"1")
    put("/stream/b.txt", b"2")
    # bounded read of the ndjson stream
    import socket
    with urllib.request.urlopen(
            f"http://{f.url}/__meta__/subscribe?since=0&prefix=/stream",
            timeout=5) as r:
        lines = []
        try:
            for line in r:
                lines.append(json.loads(line))
                if len(lines) >= 3:
                    break
        except socket.timeout:
            pass
    paths = {l["new"]["path"] for l in lines if l.get("new")}
    assert {"/stream/a.txt", "/stream/b.txt"} <= paths
