"""Chaos e2e for the overload plane, driven through the PR 4 fault
plane: a delay fault on volume reads piles requests up in the filer's
foreground queue; while that pressure lasts, background-tagged traffic
(the priority class the repair daemon and scrubber stamp) is shed with
503 + Retry-After + X-Seaweed-Shed while EVERY foreground read keeps
flowing; shed responses never open a circuit breaker; and once the
fault clears and the queue drains, shedding stops within one sampler
window."""

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from cluster_util import Cluster
from seaweedfs_tpu import faults, overload
from seaweedfs_tpu.cache.http_pool import HttpPool
from seaweedfs_tpu.utils import retry as retry_mod

# one sampler window is the overload plane's hysteresis clock (ms)
WINDOW_MS = 200.0


@pytest.fixture(scope="module")
def cluster():
    c = Cluster(n_volume_servers=2, default_replication="000")
    yield c
    faults.clear()
    c.shutdown()


@pytest.fixture()
def overloaded_filer(cluster, monkeypatch):
    """A filer whose admission plane has a deliberately tiny foreground
    pipe (2 in flight) and a deep queue, so a volume-side delay turns
    concurrent reads into visible foreground pressure."""
    monkeypatch.setenv("WEED_ADMISSION_FG_CONCURRENCY", "2")
    monkeypatch.setenv("WEED_ADMISSION_FG_QUEUE", "64")
    monkeypatch.setenv("WEED_ADMISSION_QUEUE_TIMEOUT_MS", "20000")
    monkeypatch.setenv("WEED_ADMISSION_LAG_SAMPLE_MS", str(WINDOW_MS))
    monkeypatch.setenv("WEED_ADMISSION_RETRY_AFTER_S", "1")
    # these drills need reads to actually REACH the faulted volume —
    # write-through caching would serve the just-written files from
    # the filer's chunk cache and no fg pressure would ever form
    monkeypatch.setenv("WEED_CHUNK_CACHE_WRITE_THROUGH", "0")
    fs = cluster.add_filer(chunk_size=16 * 1024)
    yield fs
    faults.clear()


def _put(filer_url: str, path: str, data: bytes) -> None:
    req = urllib.request.Request(f"http://{filer_url}{path}", data=data,
                                 method="PUT")
    with urllib.request.urlopen(req, timeout=30) as r:
        assert r.status in (200, 201)


def _get(filer_url: str, path: str, headers=None):
    """(status, body, headers) without raising on 4xx/5xx."""
    req = urllib.request.Request(f"http://{filer_url}{path}",
                                 headers=headers or {})
    try:
        with urllib.request.urlopen(req, timeout=60) as r:
            return r.status, r.read(), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, e.read(), dict(e.headers)


def _metric(filer_url: str, needle: str) -> float:
    with urllib.request.urlopen(f"http://{filer_url}/metrics",
                                timeout=10) as r:
        text = r.read().decode()
    for line in text.splitlines():
        if line.startswith(needle.split("{")[0]) and needle in line:
            return float(line.rsplit(" ", 1)[1])
    return 0.0


def _healthz(filer_url: str) -> dict:
    with urllib.request.urlopen(f"http://{filer_url}/healthz",
                                timeout=10) as r:
        return json.load(r)


def test_overload_sheds_background_first_and_recovers(cluster,
                                                      overloaded_filer):
    filer_url = overloaded_filer.url
    # the breaker counter lives in the process-wide shared registry and
    # other suites open breakers on purpose: assert no NEW opens here
    breaker_opened_before = _metric(
        filer_url, 'seaweedfs_tpu_cluster_breaker_opened_total')
    n_files = 14
    payloads = {}
    for i in range(n_files):
        data = (f"file-{i}-".encode() * 100)[:1200]
        payloads[f"/overload/f{i}"] = data
        _put(filer_url, f"/overload/f{i}", data)

    # volume reads answer slowly from here on: the filer's 2-slot
    # foreground pipe backs up and the queue becomes real pressure
    faults.set_fault("volume.read", "delay", ms=400)

    fg_results: list = []
    fg_lock = threading.Lock()

    def fg_reader(path: str, data: bytes) -> None:
        status, body, _ = _get(filer_url, path)
        with fg_lock:
            fg_results.append((path, status, body == data))

    threads = [threading.Thread(target=fg_reader, args=(p, d))
               for p, d in payloads.items()]
    for t in threads:
        t.start()
    time.sleep(0.5)  # fg queue is now occupied (2 serving, rest waiting)

    # background-tagged reads — the class the repair daemon / scrubber
    # stamp — must be refused while foreground waits, marked shed, and
    # must NOT charge the circuit breaker (threshold 1 would open on a
    # single recorded failure)
    breaker = retry_mod.CircuitBreaker(failure_threshold=1)
    pool = HttpPool(breaker=breaker, shed_retries=0)
    host = filer_url
    bg_shed = 0
    for i in range(4):
        r = pool.request(
            "GET", f"http://{filer_url}/overload/f{i}",
            headers={overload.PRIORITY_HEADER: "bg"}, timeout=10)
        if r.status == 503:
            assert r.headers.get("x-seaweed-shed") == "1"
            assert "retry-after" in r.headers
            bg_shed += 1
        time.sleep(0.05)
    assert bg_shed == 4, "bg reads admitted while fg queued"
    assert not breaker.is_open(host), \
        "shed responses must not open the circuit breaker"
    assert _healthz(filer_url)["admission"]["shedding"] is True

    # the fault clears; the queued foreground reads drain fast
    faults.clear()
    for t in threads:
        t.join(timeout=60)
    assert all(status == 200 and ok
               for _, status, ok in fg_results), fg_results
    assert len(fg_results) == n_files  # every fg read kept flowing

    # shedding stops within one sampler window of the pressure ending
    drained = time.monotonic()
    deadline = drained + (WINDOW_MS / 1000.0) + 0.8
    while time.monotonic() < deadline:
        if not _healthz(filer_url)["admission"]["shedding"]:
            break
        time.sleep(0.05)
    else:
        raise AssertionError("shedding state did not clear within a "
                             "sampler window of the fault clearing")

    # background flows again
    status, body, _ = _get(filer_url, "/overload/f0",
                           headers={overload.PRIORITY_HEADER: "bg"})
    assert status == 200 and body == payloads["/overload/f0"]

    # /metrics agrees: bg was shed, fg never was, breaker never opened
    assert _metric(filer_url,
                   'seaweedfs_tpu_filer_admission_shed_total'
                   '{cls="bg"}') >= 4
    assert _metric(filer_url,
                   'seaweedfs_tpu_filer_admission_shed_total'
                   '{cls="fg"}') == 0
    assert _metric(filer_url,
                   'seaweedfs_tpu_cluster_breaker_opened_total') \
        == breaker_opened_before
    assert _metric(filer_url,
                   'seaweedfs_tpu_filer_admission_admitted_total'
                   '{cls="fg"}') >= n_files
    pool.close()


def test_shed_tagged_repair_traffic_end_to_end(cluster, overloaded_filer):
    """The ambient-priority propagation path: a caller that binds
    CLASS_BG (as the repair daemon and scrubber do) gets the header
    injected by the pooled client automatically and sheds under
    foreground pressure without any explicit header."""
    filer_url = overloaded_filer.url
    _put(filer_url, "/overload/amb", b"ambient" * 100)

    # 800ms delay + singleflight: the 6 readers coalesce onto one slow
    # volume fetch, holding the 2-slot fg pipe (and its queue) busy for
    # a comfortably long pressure window
    faults.set_fault("volume.read", "delay", ms=800)
    blockers = [threading.Thread(
        target=_get, args=(filer_url, "/overload/amb"))
        for _ in range(6)]
    for t in blockers:
        t.start()
    time.sleep(0.25)
    pool = HttpPool(shed_retries=0)
    try:
        with overload.priority(overload.CLASS_BG):
            r = pool.request("GET",
                             f"http://{filer_url}/overload/amb",
                             timeout=10)
        assert r.status == 503
        assert r.headers.get("x-seaweed-shed") == "1"
    finally:
        faults.clear()
        for t in blockers:
            t.join(timeout=30)
        pool.close()


def test_reserved_ops_paths_reject_writes(cluster, overloaded_filer):
    """The filer's admission-exempt ops routes are reserved for ALL
    methods: a PUT to /healthz must answer 405 at the reserved route,
    not fall through aiohttp's method-mismatch resolution into the
    path catch-all as a system-classified (never metered) file write."""
    filer_url = overloaded_filer.url
    for path in ("/healthz", "/metrics", "/debug/trace", "/ui",
                 "/__meta__/subscribe"):
        req = urllib.request.Request(f"http://{filer_url}{path}",
                                     data=b"not-a-file", method="PUT")
        try:
            with urllib.request.urlopen(req, timeout=10) as r:
                raise AssertionError(f"PUT {path} accepted: {r.status}")
        except urllib.error.HTTPError as e:
            assert e.code == 405, (path, e.code)
    # and no file was created behind the shadowing GET route
    status, _, _ = _get(filer_url, "/healthz?metadata=true")
    assert status == 200  # the ops handler, not an entry listing
