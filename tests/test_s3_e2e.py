"""S3 gateway end-to-end: bucket/object CRUD, listing, multipart, copy.

The in-process analog of the reference's live S3 tests
(test/s3/basic/basic_test.go) — driven with raw HTTP/XML so no SDK is
needed."""

import json
import random
import urllib.error
import urllib.request
import xml.etree.ElementTree as ET

import pytest

from cluster_util import Cluster, free_port


@pytest.fixture(scope="module")
def cluster():
    c = Cluster(n_volume_servers=2, pulse=0.15)
    yield c
    c.shutdown()


@pytest.fixture(scope="module")
def s3(cluster):
    from aiohttp import web

    from seaweedfs_tpu.s3.s3_server import S3Server

    filer = cluster.add_filer(chunk_size=16 * 1024)
    port = free_port()
    server = S3Server(filer.url)

    async def boot():
        runner = web.AppRunner(server.app)
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", port)
        await site.start()
        return runner

    cluster.runners.append(cluster.call(boot()))
    server.url = f"127.0.0.1:{port}"
    return server


def req(s3, method, path, data=None, headers=None):
    r = urllib.request.Request(f"http://{s3.url}{path}", data=data,
                               method=method, headers=headers or {})
    return urllib.request.urlopen(r, timeout=60)


def test_bucket_lifecycle(s3):
    with req(s3, "PUT", "/mybucket") as r:
        assert r.status == 200
    with req(s3, "GET", "/") as r:
        body = r.read().decode()
    assert "mybucket" in body
    with req(s3, "HEAD", "/mybucket") as r:
        assert r.status == 200
    with req(s3, "DELETE", "/mybucket") as r:
        assert r.status == 204
    with pytest.raises(urllib.error.HTTPError) as e:
        req(s3, "HEAD", "/mybucket")
    assert e.value.code == 404


def test_object_crud(s3):
    req(s3, "PUT", "/objs")
    payload = b"s3 object body"
    with req(s3, "PUT", "/objs/folder/test.txt", data=payload,
             headers={"Content-Type": "text/plain"}) as r:
        assert r.status == 200
        assert r.headers["ETag"]
    with req(s3, "GET", "/objs/folder/test.txt") as r:
        assert r.read() == payload
        assert r.headers["Content-Type"] == "text/plain"
    with req(s3, "HEAD", "/objs/folder/test.txt") as r:
        assert int(r.headers["Content-Length"]) == len(payload)
    # range
    with req(s3, "GET", "/objs/folder/test.txt",
             headers={"Range": "bytes=3-8"}) as r:
        assert r.read() == payload[3:9]
    with req(s3, "DELETE", "/objs/folder/test.txt") as r:
        assert r.status == 204
    with pytest.raises(urllib.error.HTTPError) as e:
        req(s3, "GET", "/objs/folder/test.txt")
    assert e.value.code == 404
    # missing bucket rejected
    with pytest.raises(urllib.error.HTTPError) as e:
        req(s3, "PUT", "/nobucket/x", data=b"y")
    assert e.value.code == 404


def _keys(xml_body):
    root = ET.fromstring(xml_body)
    ns = root.tag.split("}")[0] + "}"
    return [c.find(f"{ns}Key").text
            for c in root.findall(f"{ns}Contents")], root, ns


def test_listing_v1_v2(s3):
    req(s3, "PUT", "/listb")
    for k in ["a.txt", "b/one.txt", "b/two.txt", "c.txt"]:
        req(s3, "PUT", f"/listb/{k}", data=b"x")
    with req(s3, "GET", "/listb") as r:
        keys, root, ns = _keys(r.read())
    assert keys == ["a.txt", "b/one.txt", "b/two.txt", "c.txt"]
    # delimiter: common prefixes
    with req(s3, "GET", "/listb?delimiter=/") as r:
        keys, root, ns = _keys(r.read())
    assert keys == ["a.txt", "c.txt"]
    prefixes = [p.find(f"{ns}Prefix").text
                for p in root.findall(f"{ns}CommonPrefixes")]
    assert prefixes == ["b/"]
    # prefix
    with req(s3, "GET", "/listb?prefix=b/") as r:
        keys, _, _ = _keys(r.read())
    assert keys == ["b/one.txt", "b/two.txt"]
    # v2 + pagination
    with req(s3, "GET", "/listb?list-type=2&max-keys=2") as r:
        body = r.read()
        keys, root, ns = _keys(body)
    assert len(keys) == 2
    assert root.find(f"{ns}IsTruncated").text == "true"
    token = root.find(f"{ns}NextContinuationToken").text
    with req(s3, "GET",
             f"/listb?list-type=2&continuation-token={token}") as r:
        keys2, _, _ = _keys(r.read())
    assert keys + keys2 == ["a.txt", "b/one.txt", "b/two.txt", "c.txt"]


def test_listing_prefix_plus_delimiter(s3):
    """Folder navigation: prefix="b/" + delimiter="/" must list b/'s
    direct children, not fold b/ itself into a CommonPrefix."""
    req(s3, "PUT", "/navb")
    for k in ["a.txt", "b/one.txt", "b/two.txt", "b/sub/deep.txt",
              "c.txt"]:
        req(s3, "PUT", f"/navb/{k}", data=b"x")
    with req(s3, "GET", "/navb?prefix=b/&delimiter=/") as r:
        keys, root, ns = _keys(r.read())
    prefixes = [p.find(f"{ns}Prefix").text
                for p in root.findall(f"{ns}CommonPrefixes")]
    assert keys == ["b/one.txt", "b/two.txt"]
    assert prefixes == ["b/sub/"]


def test_listing_paginated_common_prefixes(s3):
    """CommonPrefixes count toward max-keys and NextMarker advances past
    them, so pages never repeat a prefix."""
    req(s3, "PUT", "/pageb")
    for k in ["a.txt", "d1/x.txt", "d2/y.txt", "d3/z.txt", "zz.txt"]:
        req(s3, "PUT", f"/pageb/{k}", data=b"x")
    seen_keys, seen_prefixes, marker = [], [], ""
    for _ in range(10):
        url = "/pageb?delimiter=/&max-keys=2"
        if marker:
            url += f"&marker={marker}"
        with req(s3, "GET", url) as r:
            keys, root, ns = _keys(r.read())
        prefixes = [p.find(f"{ns}Prefix").text
                    for p in root.findall(f"{ns}CommonPrefixes")]
        assert len(keys) + len(prefixes) <= 2
        seen_keys += keys
        seen_prefixes += prefixes
        if root.find(f"{ns}IsTruncated").text != "true":
            break
        marker = root.find(f"{ns}NextMarker").text
    else:
        raise AssertionError("listing never terminated")
    assert seen_keys == ["a.txt", "zz.txt"]
    assert seen_prefixes == ["d1/", "d2/", "d3/"]  # no duplicates


def test_listing_marker_inside_common_prefix(s3):
    """A client-supplied marker strictly inside a prefix's subtree must
    still emit that CommonPrefix when live keys past the marker roll up
    into it (AWS semantics)."""
    req(s3, "PUT", "/markb")
    for k in ["d1/sub/a.txt", "d1/sub/m.txt", "d1/sub/z.txt",
              "d1/top.txt"]:
        req(s3, "PUT", f"/markb/{k}", data=b"x")
    with req(s3, "GET",
             "/markb?prefix=d1/&delimiter=/&marker=d1/sub/m.txt") as r:
        keys, root, ns = _keys(r.read())
    prefixes = [p.find(f"{ns}Prefix").text
                for p in root.findall(f"{ns}CommonPrefixes")]
    assert prefixes == ["d1/sub/"]
    assert keys == ["d1/top.txt"]
    # but a marker EQUAL to the prefix (it was the last item of the
    # previous page) must not re-emit it
    with req(s3, "GET",
             "/markb?prefix=d1/&delimiter=/&marker=d1/sub/") as r:
        keys, root, ns = _keys(r.read())
    prefixes = [p.find(f"{ns}Prefix").text
                for p in root.findall(f"{ns}CommonPrefixes")]
    assert prefixes == []
    assert keys == ["d1/top.txt"]


def test_multipart_upload(s3):
    req(s3, "PUT", "/mpb")
    rng = random.Random(5)
    parts = [rng.randbytes(40 * 1024), rng.randbytes(33 * 1024),
             rng.randbytes(7)]
    with req(s3, "POST", "/mpb/big.bin?uploads") as r:
        root = ET.fromstring(r.read())
    ns = root.tag.split("}")[0] + "}"
    upload_id = root.find(f"{ns}UploadId").text
    for i, data in enumerate(parts, start=1):
        with req(s3, "PUT",
                 f"/mpb/big.bin?partNumber={i}&uploadId={upload_id}",
                 data=data) as r:
            assert r.status == 200
    with req(s3, "GET", f"/mpb/big.bin?uploadId={upload_id}") as r:
        lp = r.read()
    assert lp.count(b"<Part>") == 3
    with req(s3, "POST", f"/mpb/big.bin?uploadId={upload_id}",
             data=b"<CompleteMultipartUpload/>") as r:
        assert r.status == 200
    with req(s3, "GET", "/mpb/big.bin") as r:
        assert r.read() == b"".join(parts)


def test_copy_object(s3):
    req(s3, "PUT", "/cpb")
    req(s3, "PUT", "/cpb/src.bin", data=b"copy source")
    with req(s3, "PUT", "/cpb/dst.bin",
             headers={"x-amz-copy-source": "/cpb/src.bin"}) as r:
        assert r.status == 200
    with req(s3, "GET", "/cpb/dst.bin") as r:
        assert r.read() == b"copy source"
    # source still alive after deleting the copy
    req(s3, "DELETE", "/cpb/dst.bin")
    with req(s3, "GET", "/cpb/src.bin") as r:
        assert r.read() == b"copy source"


def test_bulk_delete(s3):
    req(s3, "PUT", "/bdel")
    for k in ["x1", "x2", "x3"]:
        req(s3, "PUT", f"/bdel/{k}", data=b"d")
    body = (b"<Delete><Object><Key>x1</Key></Object>"
            b"<Object><Key>x3</Key></Object></Delete>")
    with req(s3, "POST", "/bdel?delete", data=body) as r:
        out = r.read()
    assert out.count(b"<Deleted>") == 2
    with req(s3, "GET", "/bdel") as r:
        keys, _, _ = _keys(r.read())
    assert keys == ["x2"]


def test_sigv4_auth_required():
    """Auth-enabled server rejects anonymous and accepts signed requests."""
    import datetime
    import hashlib
    import hmac as hmac_mod

    from seaweedfs_tpu.s3.s3_server import S3Server
    server = S3Server("127.0.0.1:1", access_key="AKID", secret_key="SECRET")

    class FakeQuery(dict):
        def getall(self, k):
            return [self[k]]

    # build a signed request the way a client would
    amz_date = "20260729T000000Z"
    date = "20260729"
    region, service = "us-east-1", "s3"
    headers = {"host": "example", "x-amz-date": amz_date,
               "x-amz-content-sha256": "UNSIGNED-PAYLOAD"}
    signed = ";".join(sorted(headers))
    canonical = "\n".join([
        "GET", "/", "",
        "".join(f"{h}:{headers[h]}\n" for h in sorted(headers)),
        signed, "UNSIGNED-PAYLOAD"])
    scope = f"{date}/{region}/{service}/aws4_request"
    sts = "\n".join(["AWS4-HMAC-SHA256", amz_date, scope,
                     hashlib.sha256(canonical.encode()).hexdigest()])

    def _h(key, msg):
        return hmac_mod.new(key, msg.encode(), hashlib.sha256).digest()

    k = _h(b"AWS4SECRET", date)
    k = _h(k, region)
    k = _h(k, service)
    k = _h(k, "aws4_request")
    sig = hmac_mod.new(k, sts.encode(), hashlib.sha256).hexdigest()

    class FakeRequest(dict):
        # dict base: _check_auth stashes the sigv4 context on the request
        method = "GET"
        path = "/"
        query = FakeQuery()

        def __init__(self, hdrs):
            super().__init__()
            self.headers = hdrs

    good = FakeRequest({**{k.title(): v for k, v in headers.items()},
                        "x-amz-date": amz_date,
                        "x-amz-content-sha256": "UNSIGNED-PAYLOAD",
                        "host": "example",
                        "Authorization":
                        f"AWS4-HMAC-SHA256 Credential=AKID/{scope}, "
                        f"SignedHeaders={signed}, Signature={sig}"})
    assert server._check_auth(good) is None
    bad = FakeRequest({"Authorization": "nope"})
    assert server._check_auth(bad) is not None
    tampered = FakeRequest({**good.headers,
                            "Authorization": good.headers["Authorization"]
                            .replace(sig, "0" * 64)})
    assert tampered.headers["Authorization"] != good.headers["Authorization"]
    assert server._check_auth(tampered) is not None


def test_listing_global_key_order(s3):
    """'a.txt' must sort before 'a/x' despite walk order (review regression)."""
    req(s3, "PUT", "/lexb")
    req(s3, "PUT", "/lexb/a/x", data=b"1")
    req(s3, "PUT", "/lexb/a.txt", data=b"2")
    with req(s3, "GET", "/lexb") as r:
        keys, _, _ = _keys(r.read())
    assert keys == ["a.txt", "a/x"]
    # pagination across the boundary never skips a key
    with req(s3, "GET", "/lexb?max-keys=1") as r:
        k1, root, ns = _keys(r.read())
    marker = root.find(f"{ns}NextMarker").text
    with req(s3, "GET", f"/lexb?marker={marker}") as r:
        k2, _, _ = _keys(r.read())
    assert k1 + k2 == ["a.txt", "a/x"]


def test_get_directory_key_is_404(s3):
    req(s3, "PUT", "/dirb")
    req(s3, "PUT", "/dirb/sub/obj", data=b"x")
    with pytest.raises(urllib.error.HTTPError) as e:
        req(s3, "GET", "/dirb/sub")
    assert e.value.code == 404


def test_double_bucket_create_conflicts(s3):
    req(s3, "PUT", "/dupb")
    with pytest.raises(urllib.error.HTTPError) as e:
        req(s3, "PUT", "/dupb")
    assert e.value.code == 409


def test_bogus_upload_id_404(s3):
    req(s3, "PUT", "/mpx")
    with pytest.raises(urllib.error.HTTPError) as e:
        req(s3, "POST", "/mpx/k?uploadId=deadbeef",
            data=b"<CompleteMultipartUpload/>")
    assert e.value.code == 404
    with pytest.raises(urllib.error.HTTPError) as e:
        req(s3, "PUT", "/mpx/k?partNumber=1&uploadId=deadbeef", data=b"d")
    assert e.value.code == 404
