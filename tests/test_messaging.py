"""Messaging broker: log buffer, partitioning, publish/subscribe,
filer-backed segment persistence.

Mirrors weed/messaging/ (broker pub/sub with LogBuffer segments persisted
as filer log files) and weed/util/log_buffer tests.
"""

import threading
import time

import pytest

from seaweedfs_tpu.messaging.client import (Publisher, Subscriber,
                                            pick_broker, pick_partition)
from seaweedfs_tpu.utils.log_buffer import LogBuffer, LogEntry


# --- log buffer ---

def test_log_buffer_monotonic_offsets_and_read_since():
    lb = LogBuffer()
    e1 = lb.add(b"k1", b"v1")
    e2 = lb.add(b"k2", b"v2")
    assert e2.ts_ns > e1.ts_ns
    assert [e.value for e in lb.read_since(0)] == [b"v1", b"v2"]
    assert [e.value for e in lb.read_since(e1.ts_ns)] == [b"v2"]


def test_log_buffer_flush_segments():
    segments = []
    lb = LogBuffer(flush_fn=segments.append, flush_bytes=200)
    for i in range(10):
        lb.add(f"key{i}".encode(), b"x" * 50)
    lb.flush()
    flushed = [e for seg in segments for e in seg]
    assert len(flushed) == 10
    assert lb.read_since(0) == []  # all flushed out of memory


def test_log_buffer_fanout():
    lb = LogBuffer()
    got = []
    lb.subscribe(got.append)
    lb.add(b"a", b"1")
    lb.unsubscribe(got.append)
    lb.add(b"b", b"2")
    assert [e.key for e in got] == [b"a"]


def test_log_entry_roundtrip():
    e = LogEntry(5, b"\x00key", b"\xffvalue", {"h": "1"})
    e2 = LogEntry.from_dict(e.to_dict())
    assert (e2.ts_ns, e2.key, e2.value, e2.headers) == \
        (5, b"\x00key", b"\xffvalue", {"h": "1"})


# --- partition / broker picking ---

def test_pick_partition_stable_and_spread():
    assert pick_partition(b"samekey", 8) == pick_partition(b"samekey", 8)
    seen = {pick_partition(f"k{i}".encode(), 8) for i in range(256)}
    assert len(seen) == 8  # all partitions hit


def test_pick_broker_rendezvous_stability():
    brokers = ["b1:1", "b2:1", "b3:1"]
    before = {p: pick_broker(brokers, "ns", "t", p) for p in range(32)}
    # removing one broker must only move the partitions it owned
    reduced = [b for b in brokers if b != "b2:1"]
    after = {p: pick_broker(reduced, "ns", "t", p) for p in range(32)}
    for p in range(32):
        if before[p] != "b2:1":
            assert after[p] == before[p]


# --- live broker e2e ---

@pytest.fixture(scope="module")
def cluster():
    from cluster_util import Cluster
    c = Cluster(n_volume_servers=1)
    yield c
    c.shutdown()


def _add_broker(cluster, filer_url: str = ""):
    from cluster_util import free_port

    from seaweedfs_tpu.messaging.broker import BrokerServer
    port = free_port()
    b = BrokerServer(filer_url=filer_url)
    cluster.runners.append(cluster.serve(b.app, port))
    b.url = f"127.0.0.1:{port}"
    return b


def test_publish_subscribe_roundtrip(cluster):
    b = _add_broker(cluster)
    pub = Publisher([b.url], "chat", "room1", partition_count=2)
    for i in range(20):
        pub.publish(f"user{i % 3}".encode(), f"msg-{i}".encode())
    got = []
    for p in range(2):
        sub = Subscriber([b.url], "chat", "room1", partition=p)
        got += [e.value.decode() for e in sub.stream(since=0, timeout=1.0)]
    assert sorted(got) == sorted(f"msg-{i}" for i in range(20))


def test_subscribe_tails_live_messages(cluster):
    b = _add_broker(cluster)
    pub = Publisher([b.url], "live", "topic", partition_count=1)
    sub = Subscriber([b.url], "live", "topic", partition=0)
    got = []

    def consume():
        for e in sub.stream(since=0, timeout=3.0):
            got.append(e.value)
            if len(got) >= 3:
                return

    t = threading.Thread(target=consume)
    t.start()
    time.sleep(0.3)
    for i in range(3):
        pub.publish(b"k", f"live-{i}".encode())
    t.join(timeout=5)
    assert got == [b"live-0", b"live-1", b"live-2"]


def test_segments_persist_to_filer_and_replay(cluster):
    filer = cluster.add_filer()
    b = _add_broker(cluster, filer_url=filer.url)
    pub = Publisher([b.url], "persist", "events", partition_count=1)
    # small messages but many: push past the 1MB flush threshold
    payload = b"x" * 4096
    pub.publish_many([(f"k{i}".encode(), payload) for i in range(300)])
    # force-flush remaining memory into the filer and wait for it to land
    for tp in b.partitions.values():
        tp.buffer.flush()
    b.persist.drain()
    # a fresh broker (no memory) must replay everything from the filer
    b2 = _add_broker(cluster, filer_url=filer.url)
    sub = Subscriber([b2.url], "persist", "events", partition=0)
    got = list(sub.stream(since=0, timeout=2.0))
    assert len(got) == 300
    assert all(e.value == payload for e in got)
