"""Messaging broker: log buffer, partitioning, publish/subscribe,
filer-backed segment persistence.

Mirrors weed/messaging/ (broker pub/sub with LogBuffer segments persisted
as filer log files) and weed/util/log_buffer tests.
"""

import threading
import time

import pytest

from seaweedfs_tpu.messaging.client import (Publisher, Subscriber,
                                            pick_broker, pick_partition)
from seaweedfs_tpu.utils.log_buffer import LogBuffer, LogEntry


# --- log buffer ---

def test_log_buffer_monotonic_offsets_and_read_since():
    lb = LogBuffer()
    e1 = lb.add(b"k1", b"v1")
    e2 = lb.add(b"k2", b"v2")
    assert e2.ts_ns > e1.ts_ns
    assert [e.value for e in lb.read_since(0)] == [b"v1", b"v2"]
    assert [e.value for e in lb.read_since(e1.ts_ns)] == [b"v2"]


def test_log_buffer_flush_segments():
    segments = []
    lb = LogBuffer(flush_fn=segments.append, flush_bytes=200)
    for i in range(10):
        lb.add(f"key{i}".encode(), b"x" * 50)
    lb.flush()
    flushed = [e for seg in segments for e in seg]
    assert len(flushed) == 10
    assert lb.read_since(0) == []  # all flushed out of memory


def test_log_buffer_fanout():
    lb = LogBuffer()
    got = []
    lb.subscribe(got.append)
    lb.add(b"a", b"1")
    lb.unsubscribe(got.append)
    lb.add(b"b", b"2")
    assert [e.key for e in got] == [b"a"]


def test_log_entry_roundtrip():
    e = LogEntry(5, b"\x00key", b"\xffvalue", {"h": "1"})
    e2 = LogEntry.from_dict(e.to_dict())
    assert (e2.ts_ns, e2.key, e2.value, e2.headers) == \
        (5, b"\x00key", b"\xffvalue", {"h": "1"})


# --- partition / broker picking ---

def test_pick_partition_stable_and_spread():
    assert pick_partition(b"samekey", 8) == pick_partition(b"samekey", 8)
    seen = {pick_partition(f"k{i}".encode(), 8) for i in range(256)}
    assert len(seen) == 8  # all partitions hit


def test_pick_broker_rendezvous_stability():
    brokers = ["b1:1", "b2:1", "b3:1"]
    before = {p: pick_broker(brokers, "ns", "t", p) for p in range(32)}
    # removing one broker must only move the partitions it owned
    reduced = [b for b in brokers if b != "b2:1"]
    after = {p: pick_broker(reduced, "ns", "t", p) for p in range(32)}
    for p in range(32):
        if before[p] != "b2:1":
            assert after[p] == before[p]


# --- live broker e2e ---

@pytest.fixture(scope="module")
def cluster():
    from cluster_util import Cluster
    c = Cluster(n_volume_servers=1)
    yield c
    c.shutdown()


def _add_broker(cluster, filer_url: str = ""):
    from cluster_util import free_port

    from seaweedfs_tpu.messaging.broker import BrokerServer
    port = free_port()
    b = BrokerServer(filer_url=filer_url)
    cluster.runners.append(cluster.serve(b.app, port))
    b.url = f"127.0.0.1:{port}"
    return b


def test_publish_subscribe_roundtrip(cluster):
    b = _add_broker(cluster)
    pub = Publisher([b.url], "chat", "room1", partition_count=2)
    for i in range(20):
        pub.publish(f"user{i % 3}".encode(), f"msg-{i}".encode())
    got = []
    for p in range(2):
        sub = Subscriber([b.url], "chat", "room1", partition=p)
        got += [e.value.decode() for e in sub.stream(since=0, timeout=1.0)]
    assert sorted(got) == sorted(f"msg-{i}" for i in range(20))


def test_subscribe_tails_live_messages(cluster):
    b = _add_broker(cluster)
    pub = Publisher([b.url], "live", "topic", partition_count=1)
    sub = Subscriber([b.url], "live", "topic", partition=0)
    got = []

    def consume():
        for e in sub.stream(since=0, timeout=3.0):
            got.append(e.value)
            if len(got) >= 3:
                return

    t = threading.Thread(target=consume)
    t.start()
    time.sleep(0.3)
    for i in range(3):
        pub.publish(b"k", f"live-{i}".encode())
    t.join(timeout=5)
    assert got == [b"live-0", b"live-1", b"live-2"]


def _add_registered_broker(cluster, filer):
    """Broker that registers with the filer over gRPC KeepConnected and
    participates in consistent distribution."""
    from cluster_util import free_port

    from seaweedfs_tpu.messaging.broker import BrokerServer
    port = free_port()
    b = BrokerServer(filer_url=filer.url,
                     advertise_url=f"127.0.0.1:{port}", register=True)
    runner = cluster.serve(b.app, port)
    b.url = f"127.0.0.1:{port}"
    b._runner = runner
    return b


def _wait(predicate, timeout=10.0, what=""):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if predicate():
            return
        time.sleep(0.2)
    raise AssertionError(f"timeout waiting for {what}")


@pytest.fixture(scope="module")
def broker_pair(cluster):
    import json as _json
    import urllib.request

    filer = cluster.add_filer(with_grpc=True)
    b1 = _add_registered_broker(cluster, filer)
    b2 = _add_registered_broker(cluster, filer)

    def registered():
        with urllib.request.urlopen(
                f"http://{filer.url}/__meta__/brokers", timeout=5) as r:
            return set(_json.load(r)["brokers"]) == {b1.url, b2.url}

    _wait(registered, what="both brokers registered")
    _wait(lambda: set(b1.peer_brokers) == {b1.url, b2.url}
          and set(b2.peer_brokers) == {b1.url, b2.url},
          what="peer lists converged")
    return {"filer": filer, "b1": b1, "b2": b2}


def test_multi_broker_registry_and_redirect(cluster, broker_pair):
    b1, b2 = broker_pair["b1"], broker_pair["b2"]
    # ownership is spread: with 16 partitions both brokers own some
    owners = {pick_broker(sorted([b1.url, b2.url]), "mb", "spread", p)
              for p in range(16)}
    assert owners == {b1.url, b2.url}
    # publish every partition through ONE broker: non-owned partitions
    # are 307-redirected to the owner and still land
    pub = Publisher([b1.url], "mb", "spread", partition_count=16)
    for i in range(64):
        pub.publish(f"key{i}".encode(), f"m{i}".encode())
    # each message is only on its owner: ask both brokers per partition
    got = []
    for p in range(16):
        owner = pick_broker(sorted([b1.url, b2.url]), "mb", "spread", p)
        sub = Subscriber([owner], "mb", "spread", partition=p)
        got += [e.value.decode()
                for e in sub.stream(since=0, timeout=0.5)]
    assert sorted(got) == sorted(f"m{i}" for i in range(64))
    # the partitions materialized on the owning broker, not the entry one
    b2_parts = {k for k in b2.partitions if k[0] == "mb"}
    assert b2_parts, "second broker owns no partitions?"


def test_broker_failover_on_death(cluster, broker_pair):
    import urllib.request
    filer, b1, b2 = (broker_pair["filer"], broker_pair["b1"],
                     broker_pair["b2"])
    # a partition owned by b2 while both brokers live
    ns, topic = "mb", "failover"
    victim_partition = next(
        p for p in range(32)
        if pick_broker(sorted([b1.url, b2.url]), ns, topic, p) == b2.url)
    pub = Publisher([b1.url], ns, topic,
                    partition_count=1, filer=filer.url, ack="flush")
    pub.partition_count = 1  # single logical stream

    # steer all keys into the victim partition by publishing directly
    def publish_to(partition, value):
        body_pub = Publisher([b1.url], ns, topic, filer=filer.url,
                             ack="flush")
        e_key = b"k"
        # bypass key hashing: call _post on the chosen partition
        from seaweedfs_tpu.utils.log_buffer import LogEntry
        import json as _json
        body = _json.dumps(LogEntry(0, e_key, value, {}).to_dict(),
                           separators=(",", ":")).encode() + b"\n"
        return body_pub._post(b1.url, partition, body)

    assert publish_to(victim_partition, b"before-death")["published"] == 1

    # kill b2: its KeepConnected stream drops, the registry shrinks, and
    # ownership re-converges on b1
    cluster.call(b2._runner.cleanup())

    def gone():
        with urllib.request.urlopen(
                f"http://{filer.url}/__meta__/brokers", timeout=5) as r:
            import json as _json
            return _json.load(r)["brokers"] == [b1.url]

    _wait(gone, what="dead broker deregistered")
    _wait(lambda: b1.peer_brokers == [b1.url], what="b1 registry shrink")

    assert publish_to(victim_partition, b"after-death")["published"] == 1
    # survivor serves the whole history: the pre-death message was
    # ack=flush'd into the filer, the post-death one is in memory
    sub = Subscriber([b1.url], ns, topic, partition=victim_partition)
    values = [e.value for e in sub.stream(since=0, timeout=1.0)]
    assert values == [b"before-death", b"after-death"]


def test_pub_sub_channels(cluster):
    """Channel-style wrappers (msgclient/chan_pub.go, chan_sub.go): put()
    into a named channel, iterate out of it, digests agree."""
    from seaweedfs_tpu.messaging.client import PubChannel, SubChannel

    b = _add_broker(cluster)
    with PubChannel([b.url], "jobs") as pc:
        for i in range(40):
            pc.put(f"job-{i}".encode())
    sc = SubChannel([b.url], "jobs", idle_timeout=1.0)
    got = list(sc)
    assert got == [f"job-{i}".encode() for i in range(40)]
    assert sc.digest() == pc.digest()


def test_broker_sigkill_ack_durability_contract(cluster, tmp_path):
    """The ack-level contract UNDER a kill -9 (topic_manager.go:42-116
    posture): messages acked with ack=flush survive the crash (their
    segments are in the filer); the ack=memory tail that never flushed is
    lost — exactly that tail, nothing more."""
    import os as os_mod
    import signal
    import subprocess
    import sys as sys_mod
    import time as time_mod
    import urllib.request

    from cluster_util import free_port

    filer = cluster.add_filer()
    port = free_port()
    import seaweedfs_tpu
    pkg_root = os_mod.path.dirname(
        os_mod.path.dirname(seaweedfs_tpu.__file__))
    env = dict(os_mod.environ, JAX_PLATFORMS="cpu",
               SEAWEEDFS_FORCE_CPU="1")
    env["PYTHONPATH"] = pkg_root + os_mod.pathsep + env.get(
        "PYTHONPATH", "")
    proc = subprocess.Popen(
        [sys_mod.executable, "-m", "seaweedfs_tpu.cli", "msg.broker",
         "-ip", "127.0.0.1", "-port", str(port),
         "-filer", filer.url], env=env,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    url = f"127.0.0.1:{port}"
    try:
        deadline = time_mod.time() + 20
        while True:
            try:
                urllib.request.urlopen(f"http://{url}/topics",
                                       timeout=1).close()
                break
            except Exception:
                if time_mod.time() > deadline:
                    raise
                time_mod.sleep(0.2)

        flush_pub = Publisher([url], "dur", "crash", partition_count=1,
                              ack="flush")
        for i in range(5):
            flush_pub.publish(b"k", f"durable-{i}".encode())
        mem_pub = Publisher([url], "dur", "crash", partition_count=1,
                            ack="memory")
        for i in range(7):
            mem_pub.publish(b"k", f"volatile-{i}".encode())

        # kill -9: no flush, no goodbye
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=10)
    finally:
        if proc.poll() is None:
            proc.kill()

    # a fresh broker over the same filer serves the persisted history
    b2 = _add_broker(cluster, filer_url=filer.url)
    sub = Subscriber([b2.url], "dur", "crash", partition=0)
    values = [e.value.decode() for e in sub.stream(since=0, timeout=1.0)]
    assert values == [f"durable-{i}" for i in range(5)], values
    # the loss set is exactly the unflushed ack=memory tail
    assert not any(v.startswith("volatile") for v in values)


def test_messaging_grpc_service(cluster):
    """The 4th proto service (proto/messaging.proto): Publish/Subscribe
    bidi streams, topic configuration, FindBroker."""
    import queue as queue_mod

    import grpc

    from cluster_util import free_port_with_grpc_twin

    from seaweedfs_tpu.messaging.broker import BrokerServer
    from seaweedfs_tpu.pb import messaging_pb2 as mpb
    from seaweedfs_tpu.pb.rpc import MessagingStub

    port = free_port_with_grpc_twin()
    b = BrokerServer(grpc_port=port + 10000,
                     advertise_url=f"127.0.0.1:{port}")
    cluster.runners.append(cluster.serve(b.app, port))

    ch = grpc.insecure_channel(f"127.0.0.1:{port + 10000}")
    stub = MessagingStub(ch)

    # configure + read back
    stub.ConfigureTopic(mpb.ConfigureTopicRequest(
        namespace="g", topic="t",
        configuration=mpb.TopicConfiguration(partition_count=8)),
        timeout=10)
    got = stub.GetTopicConfiguration(
        mpb.GetTopicConfigurationRequest(namespace="g", topic="t"),
        timeout=10)
    assert got.configuration.partition_count == 8

    # publish a stream of messages
    def pubs():
        yield mpb.PublishRequest(init=mpb.PublishRequest.InitMessage(
            namespace="g", topic="t", partition=0))
        for i in range(5):
            yield mpb.PublishRequest(data=mpb.Message(
                key=b"k", value=f"v{i}".encode()))

    acks = list(stub.Publish(pubs(), timeout=15))
    assert len(acks) == 5 and all(a.ack_ts_ns > 0 for a in acks)

    # subscribe from EARLIEST replays them, then tails a live message
    req_q: "queue_mod.Queue" = queue_mod.Queue()
    req_q.put(mpb.SubscriberMessage(
        init=mpb.SubscriberMessage.InitMessage(
            namespace="g", topic="t", partition=0,
            start_position=mpb.SubscriberMessage.InitMessage.EARLIEST)))

    def reqs():
        while True:
            item = req_q.get()
            if item is None:
                return
            yield item

    stream = stub.Subscribe(reqs(), timeout=20)
    values = []
    for msg in stream:
        values.append(msg.data.value)
        if len(values) == 5:
            break
    assert values == [f"v{i}".encode() for i in range(5)]
    stream.cancel()

    # FindBroker answers the rendezvous owner (single broker: itself)
    fb = stub.FindBroker(mpb.FindBrokerRequest(
        namespace="g", topic="t", partition=3), timeout=10)
    assert fb.broker == f"127.0.0.1:{port}"

    # DeleteTopic clears partitions and configuration
    stub.DeleteTopic(mpb.DeleteTopicRequest(namespace="g", topic="t"),
                     timeout=10)
    assert not [k for k in b.partitions if k[0] == "g"]
    assert ("g", "t") not in b.topic_configs
    ch.close()


def test_segments_persist_to_filer_and_replay(cluster):
    filer = cluster.add_filer()
    b = _add_broker(cluster, filer_url=filer.url)
    pub = Publisher([b.url], "persist", "events", partition_count=1)
    # small messages but many: push past the 1MB flush threshold
    payload = b"x" * 4096
    pub.publish_many([(f"k{i}".encode(), payload) for i in range(300)])
    # force-flush remaining memory into the filer and wait for it to land
    for tp in b.partitions.values():
        tp.buffer.flush()
    b.persist.drain()
    # a fresh broker (no memory) must replay everything from the filer
    b2 = _add_broker(cluster, filer_url=filer.url)
    sub = Subscriber([b2.url], "persist", "events", partition=0)
    got = list(sub.stream(since=0, timeout=2.0))
    assert len(got) == 300
    assert all(e.value == payload for e in got)
