"""weedsan (seaweedfs_tpu/sanitize) self-tests: the sanitizer must
DETECT each class of bug it claims to — a provoked lock-order
inversion, a blocked event loop, and a leaked (destroyed-while-
pending) task — and its findings must ride weedlint's fingerprint/
suppression machinery so one workflow covers both.

Each test enables the sanitizer in-process and disables it on the way
out; nothing here depends on WEED_SANITIZE being set in the
environment (that path is the chaos suites' job, wired in conftest).
"""

import asyncio
import gc
import os
import threading
import time

import pytest

from seaweedfs_tpu import sanitize
from seaweedfs_tpu.sanitize import lockgraph, loopwatch, report

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def san():
    """Armed sanitizer with a clean slate. If the session-level plugin
    already armed it (WEED_SANITIZE=1 nightly), leave it armed on the
    way out — only a fixture-local arm is fixture-local."""
    was_enabled = sanitize.enabled()
    sanitize.clear_findings()
    lockgraph.reset()
    loopwatch.reset()
    sanitize.enable(block_ms=150.0)
    loopwatch.set_threshold(150.0)   # enable() is idempotent re: config
    try:
        yield sanitize
    finally:
        if not was_enabled:
            sanitize.disable()
        else:
            loopwatch.set_threshold(sanitize.block_ms_default())
        sanitize.clear_findings()
        lockgraph.reset()
        loopwatch.reset()


# ------------------------------------------------------------ lock order

def test_lock_order_inversion_detected_with_both_stacks(san):
    """Two threads taking the same pair of locks in opposite orders —
    sequentially, so the test never actually deadlocks — must produce
    a weedsan-lock-order finding carrying BOTH acquisition stacks
    (the lockdep discipline: the cycle is the bug, not tonight's
    interleaving)."""
    lock_a = threading.Lock()
    lock_b = threading.Lock()

    def path_one():
        with lock_a:
            with lock_b:
                pass

    def path_two():
        with lock_b:
            with lock_a:
                pass

    t1 = threading.Thread(target=path_one)
    t1.start()
    t1.join()
    t2 = threading.Thread(target=path_two)
    t2.start()
    t2.join()

    found = [f for f in san.findings() if f.rule == "weedsan-lock-order"]
    assert found, "inversion went undetected"
    msg = found[0].message
    assert "path_one" in msg and "path_two" in msg, msg
    assert "this acquisition" in msg and "reverse path" in msg
    assert found[0].path.startswith("tests/")


def test_consistent_lock_order_is_clean(san):
    lock_a = threading.Lock()
    lock_b = threading.Lock()
    for _ in range(3):
        with lock_a:
            with lock_b:
                pass
    assert not [f for f in san.findings()
                if f.rule == "weedsan-lock-order"]


def test_async_lock_inversion_detected(san):
    """asyncio.Lock acquisitions feed the same digraph: two tasks
    ordering a pair of async locks oppositely is the same deadlock."""

    async def main():
        la = asyncio.Lock()
        lb = asyncio.Lock()

        async def one():
            async with la:
                async with lb:
                    pass

        async def two():
            async with lb:
                async with la:
                    pass

        await asyncio.gather(one())
        await asyncio.gather(two())

    asyncio.run(main())
    assert [f for f in san.findings() if f.rule == "weedsan-lock-order"]


# ------------------------------------------------------------ blocked loop

def test_blocked_event_loop_detected(san):
    """A coroutine that time.sleep()s on the loop past the threshold
    trips the tripwire, anchored at repo code."""

    async def main():
        async def stall():
            time.sleep(0.3)     # deliberate: the bug under test

        await asyncio.create_task(stall())

    asyncio.run(main())
    found = [f for f in san.findings()
             if f.rule == "weedsan-blocked-loop"]
    assert found, "blocked loop went undetected"
    assert "stall" in found[0].message
    assert "run_in_executor" in found[0].message


def test_fast_callbacks_do_not_trip(san):
    async def main():
        async def quick():
            await asyncio.sleep(0)

        await asyncio.create_task(quick())

    asyncio.run(main())
    assert not [f for f in san.findings()
                if f.rule == "weedsan-blocked-loop"]


# ------------------------------------------------------------ leaked task

def test_task_destroyed_while_pending_is_a_leak(san):
    """A pending task whose loop is torn down around it (never awaited,
    never cancelled) is collected pending — the classic 'Task was
    destroyed but it is pending!' — and must become a finding with the
    construction stack."""

    async def forever():
        await asyncio.get_event_loop().create_future()

    loop = asyncio.new_event_loop()
    try:
        task = loop.create_task(forever())
        loop.call_soon(loop.stop)
        loop.run_forever()      # one beat: the task starts, then stalls
    finally:
        loop.close()
    del task, loop
    gc.collect()

    found = [f for f in san.findings() if f.rule == "weedsan-task-leak"]
    assert found, "pending-task leak went undetected"
    assert "garbage-collected" in found[0].message
    assert "construction" in found[0].message
    assert found[0].path.startswith("tests/")


def test_completed_task_is_not_a_leak(san):
    async def main():
        t = asyncio.create_task(asyncio.sleep(0))
        await t

    asyncio.run(main())
    gc.collect()
    assert not [f for f in san.findings()
                if f.rule == "weedsan-task-leak"]


def test_cancelled_task_is_not_a_leak(san):
    async def main():
        async def forever():
            await asyncio.get_event_loop().create_future()

        t = asyncio.create_task(forever())
        await asyncio.sleep(0)
        t.cancel()
        try:
            await t
        except asyncio.CancelledError:
            pass

    asyncio.run(main())
    gc.collect()
    assert not [f for f in san.findings()
                if f.rule == "weedsan-task-leak"]


# ------------------------------------------------ resource leak tracking

def test_leaked_mmap_is_detected(san):
    import mmap

    def make(path):
        with open(path, "wb") as f:
            f.write(b"x" * 4096)
        fd = os.open(path, os.O_RDONLY)
        try:
            return mmap.mmap(fd, 0, prot=mmap.PROT_READ)
        finally:
            os.close(fd)

    import tempfile
    with tempfile.NamedTemporaryFile() as tf:
        mm = make(tf.name)
        del mm              # never closed
        gc.collect()

    assert [f for f in san.findings() if f.rule == "weedsan-mmap-leak"]


def test_closed_mmap_is_clean(san):
    import mmap
    import tempfile
    with tempfile.NamedTemporaryFile() as tf:
        tf.write(b"x" * 4096)
        tf.flush()
        fd = os.open(tf.name, os.O_RDONLY)
        try:
            mm = mmap.mmap(fd, 0, prot=mmap.PROT_READ)
        finally:
            os.close(fd)
        mm.close()
        del mm
        gc.collect()
    assert not [f for f in san.findings()
                if f.rule == "weedsan-mmap-leak"]


# ------------------------------- fingerprint/suppression cross-reference

def test_finding_shares_weedlint_fingerprint_scheme(san):
    """A weedsan finding's Diagnostic twin fingerprints exactly like a
    static finding anchored at the same (rule, path, line-text) — one
    baseline covers both planes."""
    from seaweedfs_tpu.analysis.engine import Diagnostic

    f = sanitize.Finding(rule="weedsan-lock-order",
                         path="tests/test_weedsan.py", line=1,
                         message="x")
    d = f.to_diagnostic()
    twin = Diagnostic(rule="weedsan-lock-order",
                      path="tests/test_weedsan.py", line=999,
                      message="different message",
                      line_text=d.line_text)
    assert d.fingerprint == twin.fingerprint  # line/message-independent
    assert d.line_text.startswith('"""')      # anchored text was read


def test_inline_suppression_reaches_runtime_finding(tmp_path, san):
    """# weedlint: disable=weedsan-task-leak at the anchor line drops
    the runtime finding through the same Module.suppressed machinery."""
    rel = "tests/_weedsan_suppressed_fixture.py"
    p = os.path.join(REPO_ROOT, rel)
    with open(p, "w") as f:
        f.write("def spawn(loop, coro):\n"
                "    return loop.create_task(coro)"
                "  # weedlint: disable=weedsan-task-leak\n")
    try:
        hit = sanitize.Finding(rule="weedsan-task-leak", path=rel,
                               line=2, message="leak")
        miss = sanitize.Finding(rule="weedsan-lock-order", path=rel,
                                line=2, message="other rule")
        kept = report.unsuppressed([hit, miss])
        assert kept == [miss]
    finally:
        os.unlink(p)


def test_baseline_matches_runtime_finding(tmp_path, san):
    """A baseline entry written from the Diagnostic twin grandfathers
    the runtime finding — the ONE workflow requirement."""
    from seaweedfs_tpu.analysis.engine import Baseline
    rel = "tests/_weedsan_baseline_fixture.py"
    p = os.path.join(REPO_ROOT, rel)
    with open(p, "w") as f:
        f.write("HELD = object()\n")
    bl = tmp_path / "bl.json"
    try:
        f0 = sanitize.Finding(rule="weedsan-session-leak", path=rel,
                              line=1, message="leaked session")
        Baseline.from_findings([f0.to_diagnostic()]).write(str(bl))
        assert report.unsuppressed([f0], baseline_path=str(bl)) == []
        assert report.unsuppressed([f0]) == [f0]  # empty tree baseline
    finally:
        os.unlink(p)


def test_enable_disable_restores_primitives(san):
    """disable() puts the real constructors back (the fixture calls
    disable; verify from a nested arm/disarm cycle)."""
    sanitize.disable()
    assert threading.Lock is lockgraph._real_Lock
    assert asyncio.Lock is lockgraph._real_async_Lock
    sanitize.enable()
    assert threading.Lock is not lockgraph._real_Lock
