"""Backend abstraction + cloud tier.

Mirrors the reference's backend layer (weed/storage/backend/backend.go,
s3_backend/s3_backend.go) and warm tiering (volume_tier.go:15-50): a sealed
volume's .dat moves to an object store, the .idx stays local, reads proxy
through the remote backend, and a `.vif` sidecar makes it survive reload.
"""

import os
import random

import pytest

from seaweedfs_tpu.storage import backend
from seaweedfs_tpu.storage.needle import Needle
from seaweedfs_tpu.storage.store import Store
from seaweedfs_tpu.storage.volume import Volume, VolumeReadOnly


def fill(v: Volume, n=20, seed=1):
    rng = random.Random(seed)
    payloads = {}
    for i in range(1, n + 1):
        data = bytes(rng.getrandbits(8) for _ in range(rng.randint(10, 4000)))
        payloads[i] = data
        v.write_needle(Needle(cookie=0x100 + i, id=i, data=data))
    return payloads


def test_disk_file_positioned_io(tmp_path):
    f = backend.DiskFile(str(tmp_path / "x"), create=True)
    f.write_at(b"hello world", 0)
    f.write_at(b"WORLD", 6)
    assert f.read_at(11, 0) == b"hello WORLD"
    assert f.size() == 11
    f.truncate(5)
    assert f.size() == 5
    f.close()
    assert f.closed


def test_local_object_store_roundtrip(tmp_path):
    src = tmp_path / "blob"
    src.write_bytes(b"0123456789" * 100)
    store = backend.LocalObjectStore(str(tmp_path / "bucket"))
    store.put("k1.dat", str(src))
    assert store.size("k1.dat") == 1000
    assert store.get_range("k1.dat", 10, 10) == b"0123456789"
    dest = tmp_path / "back"
    store.get_to_file("k1.dat", str(dest))
    assert dest.read_bytes() == src.read_bytes()
    store.delete("k1.dat")
    with pytest.raises(FileNotFoundError):
        store.size("k1.dat")


def test_remote_file_block_cache(tmp_path):
    src = tmp_path / "blob"
    src.write_bytes(bytes(range(256)) * 16)
    store = backend.LocalObjectStore(str(tmp_path / "bucket"))
    store.put("k", str(src))
    rf = backend.RemoteFile(store, "k", 4096)
    assert rf.read_at(16, 0) == bytes(range(16))
    assert rf.read_at(10, 250) == bytes([250, 251, 252, 253, 254, 255,
                                         0, 1, 2, 3])
    assert rf.read_at(100, 4090) == bytes(range(250, 256))  # clamped at EOF
    with pytest.raises(IOError):
        rf.write_at(b"x", 0)


def test_tier_upload_read_download_cycle(tmp_path):
    store_dir = str(tmp_path / "data")
    bucket = str(tmp_path / "bucket")
    os.makedirs(store_dir)
    st = Store([store_dir], max_volume_counts=[4], coder_name="numpy")
    v = st.add_volume(1)
    payloads = fill(v)

    spec = {"type": "local_store", "directory": bucket}
    info = st.tier_upload(1, spec)
    assert not os.path.exists(os.path.join(store_dir, "1.dat"))
    assert os.path.exists(os.path.join(store_dir, "1.vif"))
    assert info["files"][0]["key"] == "1.dat"

    # reads proxy to the object store
    v = st.find_volume(1)
    assert v.is_remote
    for i, data in payloads.items():
        assert v.read_needle(i).data == data
    # writes rejected
    with pytest.raises(VolumeReadOnly):
        v.write_needle(Needle(cookie=1, id=999, data=b"nope"))

    # heartbeat still reports it
    hb = st.heartbeat()
    assert any(vol["id"] == 1 for vol in hb["volumes"])

    # reload from disk: the .vif makes it come back tiered
    st.close()
    st2 = Store([store_dir], max_volume_counts=[4], coder_name="numpy")
    v2 = st2.find_volume(1)
    assert v2 is not None and v2.is_remote
    for i, data in payloads.items():
        assert v2.read_needle(i).data == data

    # download brings it back local
    st2.tier_download(1)
    assert os.path.exists(os.path.join(store_dir, "1.dat"))
    assert not os.path.exists(os.path.join(store_dir, "1.vif"))
    v3 = st2.find_volume(1)
    assert not v3.is_remote
    for i, data in payloads.items():
        assert v3.read_needle(i).data == data
    st2.close()


def test_tier_upload_refuses_double(tmp_path):
    store_dir = str(tmp_path / "data")
    os.makedirs(store_dir)
    st = Store([store_dir], max_volume_counts=[4], coder_name="numpy")
    v = st.add_volume(1)
    fill(v, n=3)
    spec = {"type": "local_store", "directory": str(tmp_path / "b")}
    st.tier_upload(1, spec)
    with pytest.raises(ValueError):
        st.tier_upload(1, spec)
    st.close()


def test_sigv4_signer_matches_gateway_verifier(tmp_path):
    """The client signer must produce signatures s3_server accepts —
    verified by replaying the signed request through the same math the
    server uses."""
    from seaweedfs_tpu.s3.sigv4 import sign_request
    headers = sign_request("PUT", "http://127.0.0.1:8333/b/k.dat",
                           {}, b"payload", "ak", "sk")
    assert headers["Authorization"].startswith("AWS4-HMAC-SHA256 Credential=ak/")
    assert "SignedHeaders=host;x-amz-content-sha256;x-amz-date" \
        in headers["Authorization"]
    import hashlib
    assert headers["x-amz-content-sha256"] == \
        hashlib.sha256(b"payload").hexdigest()
