"""The hand-rolled data-plane listener (server/fastpath.py).

run_volume_server's public port speaks the minimal HTTP/1.1 protocol and
proxies the non-data surface to the internal aiohttp app; these tests
exercise exactly that wiring (the in-process Cluster used by other suites
serves aiohttp directly, so this file is the fastpath's coverage).
"""

import asyncio
import json
import socket
import threading

import pytest

from seaweedfs_tpu.server.volume_server import run_volume_server
from seaweedfs_tpu.storage.store import Store


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class _Srv:
    """run_volume_server in a background loop thread."""

    def __init__(self, tmpdir: str, whitelist=None):
        self.port = _free_port()
        self.store = Store([tmpdir])
        self.store.add_volume(1)
        self.loop = asyncio.new_event_loop()
        kwargs = {}
        if whitelist is not None:
            from seaweedfs_tpu.security.guard import Guard
            kwargs["guard"] = Guard(whitelist=whitelist)
        self.runner = None

        def run():
            asyncio.set_event_loop(self.loop)
            self.runner = self.loop.run_until_complete(run_volume_server(
                "127.0.0.1", self.port, self.store,
                master_url="127.0.0.1:1",  # no master: heartbeats warn only
                pulse_seconds=3600, **kwargs))
            self.loop.run_forever()

        self.th = threading.Thread(target=run, daemon=True)
        self.th.start()
        import time
        deadline = time.time() + 10
        while time.time() < deadline:
            try:
                socket.create_connection(("127.0.0.1", self.port),
                                         timeout=0.2).close()
                return
            except OSError:
                time.sleep(0.05)
        raise TimeoutError("fastpath server did not listen")

    def stop(self):
        async def halt():
            await self.runner.cleanup()
        asyncio.run_coroutine_threadsafe(halt(), self.loop).result(10)
        self.loop.call_soon_threadsafe(self.loop.stop)
        self.th.join(5)


def _req(port, method, path, body=b"", headers=None):
    """One raw HTTP/1.1 request on a fresh connection."""
    s = socket.create_connection(("127.0.0.1", port), timeout=10)
    hs = "".join(f"{k}: {v}\r\n" for k, v in (headers or {}).items())
    s.sendall(f"{method} {path} HTTP/1.1\r\nHost: x\r\n"
              f"Content-Length: {len(body)}\r\n{hs}\r\n".encode() + body)
    buf = b""
    while b"\r\n\r\n" not in buf:
        chunk = s.recv(1 << 16)
        if not chunk:
            break
        buf += chunk
    head, _, rest = buf.partition(b"\r\n\r\n")
    status = int(head.split(b" ", 2)[1])
    length = None
    for line in head.split(b"\r\n")[1:]:
        if line.lower().startswith(b"content-length"):
            length = int(line.split(b":")[1])
    if length is not None and method != "HEAD":
        while len(rest) < length:
            chunk = s.recv(1 << 16)
            if not chunk:
                break
            rest += chunk
        rest = rest[:length]
    s.close()
    return status, dict(
        (line.split(b":", 1)[0].decode().lower(),
         line.split(b":", 1)[1].strip().decode())
        for line in head.split(b"\r\n")[1:] if b":" in line), rest


def _multipart(data: bytes, filename="f.bin",
               ctype="application/octet-stream"):
    b = "fastb0undary"
    body = (f'--{b}\r\nContent-Disposition: form-data; name="file"; '
            f'filename="{filename}"\r\nContent-Type: {ctype}\r\n\r\n'
            ).encode() + data + f"\r\n--{b}--\r\n".encode()
    return body, f"multipart/form-data; boundary={b}"


@pytest.fixture()
def srv(tmp_path):
    s = _Srv(str(tmp_path))
    yield s
    s.stop()


FID = "1,42deadbeef"


def test_write_read_head_delete(srv):
    payload = b"\x01\x02fastpath payload" * 40
    body, ct = _multipart(payload)
    status, _, resp = _req(srv.port, "POST", f"/{FID}", body,
                           {"Content-Type": ct})
    assert status == 201
    meta = json.loads(resp)
    # size is the STORED length (post write-path gzip), matching the
    # aiohttp handler's semantics
    assert 0 < meta["size"] <= len(payload)

    status, hdrs, got = _req(srv.port, "GET", f"/{FID}")
    assert status == 200 and got == payload
    assert hdrs.get("etag")

    # HEAD reports the real size with no body
    status, hdrs, got = _req(srv.port, "HEAD", f"/{FID}")
    assert status == 200 and got == b""
    assert int(hdrs["content-length"]) == len(payload)

    # conditional read
    status, _, _ = _req(srv.port, "GET", f"/{FID}",
                        headers={"If-None-Match": hdrs["etag"]})
    assert status == 304

    # range requests proxy to aiohttp and still work
    status, _, got = _req(srv.port, "GET", f"/{FID}",
                          headers={"Range": "bytes=2-5"})
    assert status == 206 and got == payload[2:6]

    status, _, resp = _req(srv.port, "DELETE", f"/{FID}")
    assert status == 200 and json.loads(resp)["size"] > 0
    status, _, _ = _req(srv.port, "GET", f"/{FID}")
    assert status == 404


def test_proxied_surface_and_errors(srv):
    # /status is served by the aiohttp app through the loopback proxy
    status, _, resp = _req(srv.port, "GET", "/status")
    assert status == 200
    assert "volumes" in json.loads(resp)
    # unknown fid forms
    status, _, _ = _req(srv.port, "GET", "/nofid")
    assert status == 400
    # missing needle 404s via the proxied repair path
    status, _, _ = _req(srv.port, "GET", "/1,99aaaaaaaa")
    assert status == 404
    # oversize declared body is rejected before buffering
    s = socket.create_connection(("127.0.0.1", srv.port), timeout=10)
    s.sendall(b"POST /" + FID.encode() + b" HTTP/1.1\r\nHost: x\r\n"
              b"Content-Length: 999999999999\r\n\r\n")
    buf = b""
    while b"\r\n\r\n" not in buf:
        chunk = s.recv(1 << 16)
        if not chunk:
            break
        buf += chunk
    assert b" 413 " in buf.split(b"\r\n", 1)[0]
    s.close()


def test_proxied_head_no_hang(srv):
    """HEAD responses on the proxied path carry Content-Length but no
    body; the relay must not wait for body bytes (it used to stall until
    aiohttp's keep-alive timeout, ~75s)."""
    import time
    t0 = time.time()
    # missing needle -> proxied repair path -> 404 with a JSON error body
    # advertised in Content-Length but never sent for HEAD
    status, _, got = _req(srv.port, "HEAD", "/1,99aaaaaaaa")
    assert status == 404 and got == b""
    # proxied admin surface
    status, _, got = _req(srv.port, "HEAD", "/status")
    assert status == 200 and got == b""
    assert time.time() - t0 < 10

    # the per-connection loop is serial: a request pipelined after a
    # proxied HEAD must still be answered promptly
    s = socket.create_connection(("127.0.0.1", srv.port), timeout=10)
    s.sendall(b"HEAD /1,99aaaaaaaa HTTP/1.1\r\nHost: x\r\n"
              b"Content-Length: 0\r\n\r\n"
              b"GET /status HTTP/1.1\r\nHost: x\r\n"
              b"Content-Length: 0\r\n\r\n")
    buf = b""
    deadline = time.time() + 10
    while buf.count(b"HTTP/1.1") < 2 and time.time() < deadline:
        chunk = s.recv(1 << 16)
        if not chunk:
            break
        buf += chunk
    s.close()
    assert buf.count(b"HTTP/1.1") >= 2 and b" 200 " in buf


def test_malformed_content_length_400(srv):
    s = socket.create_connection(("127.0.0.1", srv.port), timeout=10)
    s.sendall(b"POST /" + FID.encode() + b" HTTP/1.1\r\nHost: x\r\n"
              b"Content-Length: banana\r\n\r\n")
    buf = b""
    while b"\r\n\r\n" not in buf:
        chunk = s.recv(1 << 16)
        if not chunk:
            break
        buf += chunk
    assert b" 400 " in buf.split(b"\r\n", 1)[0]
    s.close()
    # negative declared length is equally malformed
    s = socket.create_connection(("127.0.0.1", srv.port), timeout=10)
    s.sendall(b"POST /" + FID.encode() + b" HTTP/1.1\r\nHost: x\r\n"
              b"Content-Length: -5\r\n\r\n")
    buf = b""
    while b"\r\n\r\n" not in buf:
        chunk = s.recv(1 << 16)
        if not chunk:
            break
        buf += chunk
    assert b" 400 " in buf.split(b"\r\n", 1)[0]
    s.close()


def test_keepalive_many_requests(srv):
    payload = b"ka" * 100
    body, ct = _multipart(payload)
    s = socket.create_connection(("127.0.0.1", srv.port), timeout=10)
    for i in range(20):
        s.sendall(f"POST /1,{i+1:x}00000011 HTTP/1.1\r\nHost: x\r\n"
                  f"Content-Type: {ct}\r\n"
                  f"Content-Length: {len(body)}\r\n\r\n".encode() + body)
        buf = b""
        while b"\r\n\r\n" not in buf:
            buf += s.recv(1 << 16)
        head, _, rest = buf.partition(b"\r\n\r\n")
        assert b" 201 " in head.split(b"\r\n", 1)[0]
        ln = int([l for l in head.split(b"\r\n")
                  if l.lower().startswith(b"content-length")][0]
                 .split(b":")[1])
        while len(rest) < ln:
            rest += s.recv(1 << 16)
    s.close()


def test_whitelist_passes_through_proxy(tmp_path):
    # a whitelist that includes the client must admit BOTH inline and
    # proxied requests (the internal listener sees 127.0.0.1; the token
    # header carries the original verification through)
    s = _Srv(str(tmp_path), whitelist=["127.0.0.1"])
    try:
        status, _, _ = _req(s.port, "GET", "/status")
        assert status == 200
        payload = b"wl" * 10
        body, ct = _multipart(payload)
        status, _, _ = _req(s.port, "POST", f"/{FID}", body,
                            {"Content-Type": ct})
        assert status == 201
    finally:
        s.stop()


def test_fastpath_admission_hook_sheds(tmp_path, monkeypatch):
    """The raw-socket listener bypasses aiohttp middleware, so the
    overload plane hooks it explicitly: with a 1-slot foreground pipe
    and no queue, a second concurrent read sheds 503 with the shed
    marker + Retry-After, bg-tagged reads shed under that pressure, and
    the inline fast read fires the volume.read fault point (which is
    what makes this test's service time controllable at all)."""
    import time

    from seaweedfs_tpu import faults

    monkeypatch.setenv("WEED_ADMISSION_FG_CONCURRENCY", "1")
    monkeypatch.setenv("WEED_ADMISSION_FG_QUEUE", "0")
    monkeypatch.setenv("WEED_ADMISSION_LAG_SAMPLE_MS", "100")
    monkeypatch.setenv("WEED_ADMISSION_RETRY_AFTER_S", "1")
    srv = _Srv(str(tmp_path))
    try:
        payload = b"shed me" * 10
        body, ct = _multipart(payload)
        status, _, _ = _req(srv.port, "POST", f"/{FID}", body,
                            {"Content-Type": ct})
        assert status == 201

        # unfaulted read works and is admitted
        status, _, got = _req(srv.port, "GET", f"/{FID}")
        assert status == 200 and got == payload

        # make the inline fast read slow via the fault plane (the hook
        # added alongside admission: fastpath fires volume.read too)
        faults.set_fault("volume.read", "delay", ms=600)
        t = threading.Thread(target=_req,
                             args=(srv.port, "GET", f"/{FID}"))
        t.start()
        time.sleep(0.2)  # the slow read owns the single fg slot
        status, hdrs, _ = _req(srv.port, "GET", f"/{FID}")
        assert status == 503
        assert hdrs.get("x-seaweed-shed") == "1"
        assert int(hdrs.get("retry-after", "0")) >= 1
        # background is locked out while fg is under pressure
        status, hdrs, _ = _req(srv.port, "GET", f"/{FID}",
                               headers={"X-Seaweed-Priority": "bg"})
        assert status == 503 and hdrs.get("x-seaweed-shed") == "1"
        t.join(10)
        faults.clear()
        # pressure gone (one sampler window): everything flows again
        time.sleep(0.15)
        status, _, got = _req(srv.port, "GET", f"/{FID}",
                              headers={"X-Seaweed-Priority": "bg"})
        assert status == 200 and got == payload
    finally:
        faults.clear()
        srv.stop()


def test_fastpath_sheds_before_buffering_body(tmp_path, monkeypatch):
    """Admission runs from the HEADERS, before the body is buffered: a
    write that will be shed must be refused while its body is still on
    the wire, or a storm of declared-large POSTs buffers gigabytes of
    bodies that were never going to be admitted (the memory-collapse
    mode the overload plane exists to stop).  The shed answer arrives
    with none of the body sent, and the connection closes (an unread
    body makes the framing unrecoverable)."""
    import time

    from seaweedfs_tpu import faults

    monkeypatch.setenv("WEED_ADMISSION_FG_CONCURRENCY", "1")
    monkeypatch.setenv("WEED_ADMISSION_FG_QUEUE", "0")
    monkeypatch.setenv("WEED_ADMISSION_LAG_SAMPLE_MS", "2000")
    srv = _Srv(str(tmp_path))
    try:
        payload = b"hold the slot"
        body, ct = _multipart(payload)
        status, _, _ = _req(srv.port, "POST", f"/{FID}", body,
                            {"Content-Type": ct})
        assert status == 201
        faults.set_fault("volume.read", "delay", ms=800)
        t = threading.Thread(target=_req,
                             args=(srv.port, "GET", f"/{FID}"))
        t.start()
        time.sleep(0.2)  # the slow read owns the single fg slot
        s = socket.create_connection(("127.0.0.1", srv.port), timeout=5)
        try:
            s.sendall(f"POST /{FID} HTTP/1.1\r\nHost: x\r\n"
                      f"Content-Length: 10000000\r\n"
                      f"Content-Type: multipart/form-data; boundary=q"
                      f"\r\n\r\n".encode())  # headers only — no body
            s.settimeout(3.0)
            t0 = time.monotonic()
            data = s.recv(65536)
            took = time.monotonic() - t0
            line = data.split(b"\r\n", 1)[0]
            assert b"503" in line, data
            assert b"x-seaweed-shed: 1" in data.lower(), data
            # answered from the headers alone, not after a body wait
            assert took < 1.0, took
            # unread body in flight -> server closes the connection
            assert s.recv(4096) == b""
        finally:
            s.close()
        t.join(10)
    finally:
        faults.clear()
        srv.stop()


def test_mark_internal_strips_spoofed_swfs_headers():
    """A client-sent X-Swfs-Tunnel on a proxied (already-admitted)
    request would make the aiohttp middleware meter it a SECOND time —
    with fg slots held at the listener that deadlocks the class into
    queue-timeout sheds. All client copies of the internal headers are
    stripped before the listener injects its own."""
    import types
    from seaweedfs_tpu.server.fastpath import FastVolumeProtocol

    p = FastVolumeProtocol.__new__(FastVolumeProtocol)
    p.server = types.SimpleNamespace(_internal_token="tok123")
    p.peer_ip = "10.0.0.9"
    raw = (b"GET /1,abc HTTP/1.1\r\n"
           b"Host: x\r\n"
           b"X-Swfs-Tunnel: 1\r\n"
           b"X-Swfs-Internal: guessed\r\n"
           b"X-Swfs-Peer: 8.8.8.8\r\n"
           b"Accept: */*\r\n"
           b"\r\nBODY")
    parts = p._mark_internal(raw)
    # the body rides as an uncopied view into the original buffer (a
    # proxied 256 MB PUT must not pay full-buffer copies here)
    assert isinstance(parts[-1], memoryview)
    assert parts[-1].obj is raw
    marked = b"".join(bytes(x) for x in parts)
    head = marked.split(b"\r\n\r\n", 1)[0]
    # exactly one copy of each injected header, ours
    assert head.count(b"X-Swfs-Internal:") == 1
    assert b"X-Swfs-Internal: tok123" in head
    assert head.count(b"X-Swfs-Peer:") == 1
    assert b"X-Swfs-Peer: 10.0.0.9" in head
    assert b"X-Swfs-Tunnel" not in head       # spoofed marker gone
    assert b"guessed" not in head and b"8.8.8.8" not in head
    assert b"Host: x" in head and b"Accept: */*" in head
    assert marked.endswith(b"\r\n\r\nBODY")
    # the real tunnel path still marks itself
    marked = b"".join(bytes(x)
                      for x in p._mark_internal(raw, tunnel=True))
    head = marked.split(b"\r\n\r\n", 1)[0]
    assert head.count(b"X-Swfs-Tunnel:") == 1
    assert b"X-Swfs-Tunnel: 1" in head


def test_fastpath_emits_wide_events(srv):
    """The raw-socket listener bypasses aiohttp middleware, so it emits
    its own wide events: one canonical record per fast-served request,
    carrying the propagated trace id, priority class, and byte counts —
    and no duplicate record for the proxied surface (the aiohttp
    middleware owns those)."""
    import time

    from seaweedfs_tpu.observe import wideevents

    def _wait_events(trace, n=1, deadline_s=5.0):
        # the record lands in the listener's finally block AFTER the
        # response bytes hit the wire — poll rather than race it
        deadline = time.time() + deadline_s
        while time.time() < deadline:
            evs = wideevents.events(trace=trace)
            if len(evs) >= n:
                return evs
            time.sleep(0.02)
        return wideevents.events(trace=trace)

    wideevents.reset()
    payload = b"wide event payload" * 30
    body, ct = _multipart(payload)
    status, _, _ = _req(srv.port, "POST", f"/{FID}", body,
                        {"Content-Type": ct})
    assert status == 201

    tid = "feedfacefastwide"
    status, _, got = _req(srv.port, "GET", f"/{FID}",
                          headers={"X-Seaweed-Trace": f"{tid}:",
                                   "X-Seaweed-Priority": "bg"})
    assert status == 200 and got == payload

    evs = _wait_events(tid)
    assert len(evs) == 1, evs
    ev = evs[0]
    assert ev["svc"] == "volume"
    assert ev["name"].startswith("fast GET /")
    assert ev["cls"] == "bg"
    assert ev["status"] == 200
    assert ev["bytes_out"] == len(payload)
    assert ev["shed"] is False
    assert ev["dur_us"] > 0

    # the proxied surface (/status goes through the loopback tunnel to
    # aiohttp) produces exactly ONE event — the middleware's, not a
    # second one from the fastpath listener
    tid2 = "feedfaceproxied0"
    status, _, _ = _req(srv.port, "GET", "/status",
                        headers={"X-Seaweed-Trace": f"{tid2}:"})
    assert status == 200
    evs = _wait_events(tid2)
    time.sleep(0.2)  # give a would-be duplicate emitter time to land
    evs = wideevents.events(trace=tid2)
    assert len(evs) == 1, evs
    assert not evs[0]["name"].startswith("fast ")
    wideevents.reset()


def test_fastpath_shed_emits_wide_event(tmp_path, monkeypatch):
    """A request refused at the fastpath admission gate still leaves a
    wide event (shed=True, 503) — sheds are exactly the traffic a tail
    investigation must be able to see."""
    import time

    from seaweedfs_tpu import faults
    from seaweedfs_tpu.observe import wideevents

    monkeypatch.setenv("WEED_ADMISSION_FG_CONCURRENCY", "1")
    monkeypatch.setenv("WEED_ADMISSION_FG_QUEUE", "0")
    monkeypatch.setenv("WEED_ADMISSION_LAG_SAMPLE_MS", "100")
    srv = _Srv(str(tmp_path))
    try:
        payload = b"shed and observe" * 8
        body, ct = _multipart(payload)
        status, _, _ = _req(srv.port, "POST", f"/{FID}", body,
                            {"Content-Type": ct})
        assert status == 201

        wideevents.reset()
        faults.set_fault("volume.read", "delay", ms=600)
        t = threading.Thread(target=_req,
                             args=(srv.port, "GET", f"/{FID}"))
        t.start()
        time.sleep(0.2)  # the slow read owns the single fg slot
        tid = "feedfaceshedwide"
        status, hdrs, _ = _req(srv.port, "GET", f"/{FID}",
                               headers={"X-Seaweed-Trace": f"{tid}:"})
        assert status == 503 and hdrs.get("x-seaweed-shed") == "1"
        t.join(10)
        faults.clear()

        deadline = time.time() + 5
        while time.time() < deadline:
            evs = wideevents.events(trace=tid)
            if evs:
                break
            time.sleep(0.02)
        assert len(evs) == 1, evs
        assert evs[0]["shed"] is True
        assert evs[0]["status"] == 503
        # the shed tail is queryable the way cluster.tail reads it
        assert any(e["trace"] == tid
                   for e in wideevents.events(shed=True))
        wideevents.reset()
    finally:
        faults.clear()
        srv.stop()


def test_hot_parse_allocations_pinned():
    """The per-request parse path must not allocate on the benchmark
    shapes: the no-query GET shares ONE dict (_EMPTY_QUERY) and
    _HeaderView is slotted so token extraction costs one fixed-size
    object, not a dict copy. Regressions here (an f-string, a
    per-request dict, a dropped __slots__) show up as net block
    growth across iterations."""
    import gc
    import sys

    from seaweedfs_tpu.server import fastpath

    # the no-query fast shape returns the module-level shared dict
    assert fastpath._parse_query("") is fastpath._EMPTY_QUERY
    assert fastpath._parse_query("") is fastpath._parse_query("")
    # escaped and plain pairs still decode like aiohttp would
    assert fastpath._parse_query("a=1&b=x%20y") == {"a": "1", "b": "x y"}
    # _HeaderView carries no per-instance __dict__
    view = fastpath._HeaderView({b"authorization": b"Bearer t"})
    assert not hasattr(view, "__dict__")
    assert view.get("Authorization") == "Bearer t"

    headers = {b"content-length": b"0", b"authorization": b""}

    def hot() -> None:
        q = fastpath._parse_query("")
        assert not q
        fastpath._HeaderView(headers).get("Authorization")

    for _ in range(200):  # warm caches/interning before measuring
        hot()
    gc.collect()
    before = sys.getallocatedblocks()
    for _ in range(5000):
        hot()
    gc.collect()
    grown = sys.getallocatedblocks() - before
    # transient objects are freed each iteration; anything that sticks
    # (a cache keyed per call, a leaked view) grows net blocks linearly
    assert grown < 500, f"hot parse path leaked {grown} blocks"
    # callers treat query dicts as read-only; the shared empty dict
    # must never pick up keys from a request
    assert len(fastpath._EMPTY_QUERY) == 0
