"""Filer logic tests: chunk algebra (modeled on the reference's
filechunks_test.go randomized/merge tests), stores, namespace core."""

import random
import sqlite3

import pytest

from seaweedfs_tpu.filer.chunks import (FileChunk, compact_chunks, etag,
                                        non_overlapping_visible_intervals,
                                        read_plan, total_size)
from seaweedfs_tpu.filer.entry import new_directory, new_file
from seaweedfs_tpu.filer.filer import Filer
from seaweedfs_tpu.filer.abstract_sql import SqliteStore
from seaweedfs_tpu.filer.stores import MemoryStore, create_store


# ---------- chunk algebra ----------

def test_single_chunk():
    chunks = [FileChunk("1,ab", 0, 100, mtime=1)]
    v = non_overlapping_visible_intervals(chunks)
    assert len(v) == 1 and (v[0].start, v[0].stop) == (0, 100)
    plan = read_plan(chunks, 10, 50)
    assert len(plan) == 1
    assert plan[0].offset_in_chunk == 10 and plan[0].size == 50


def test_full_overwrite():
    chunks = [FileChunk("1,a", 0, 100, mtime=1),
              FileChunk("2,b", 0, 100, mtime=2)]
    v = non_overlapping_visible_intervals(chunks)
    assert len(v) == 1 and v[0].fid == "2,b"
    live, garbage = compact_chunks(chunks)
    assert [c.fid for c in live] == ["2,b"]
    assert [c.fid for c in garbage] == ["1,a"]


def test_partial_overwrite_middle():
    chunks = [FileChunk("1,a", 0, 100, mtime=1),
              FileChunk("2,b", 30, 40, mtime=2)]
    v = non_overlapping_visible_intervals(chunks)
    assert [(x.start, x.stop, x.fid) for x in v] == [
        (0, 30, "1,a"), (30, 70, "2,b"), (70, 100, "1,a")]
    plan = read_plan(chunks, 20, 60)
    assert [(p.fid, p.offset_in_chunk, p.size, p.logic_offset)
            for p in plan] == [
        ("1,a", 20, 10, 20), ("2,b", 0, 40, 30), ("1,a", 70, 10, 70)]


def test_append_chunks():
    chunks = [FileChunk("1,a", 0, 100, mtime=1),
              FileChunk("2,b", 100, 50, mtime=2)]
    assert total_size(chunks) == 150
    v = non_overlapping_visible_intervals(chunks)
    assert len(v) == 2


def test_sparse_file_hole():
    chunks = [FileChunk("1,a", 0, 10, mtime=1),
              FileChunk("2,b", 100, 10, mtime=2)]
    assert total_size(chunks) == 110
    plan = read_plan(chunks, 0, 110)
    assert [(p.logic_offset, p.size) for p in plan] == [(0, 10), (100, 10)]


def test_randomized_overwrites_differential():
    """Write random ranges into a reference bytearray and via the chunk
    algebra; reads must agree (the reference's randomized test pattern)."""
    rng = random.Random(0)
    size = 1000
    truth = bytearray(size)
    data_by_fid = {}
    chunks = []
    for i in range(60):
        off = rng.randrange(0, size - 1)
        ln = rng.randrange(1, size - off)
        fid = f"9,{i:04x}0000"
        payload = bytes([rng.randrange(1, 256)]) * ln
        truth[off:off + ln] = payload
        data_by_fid[fid] = payload
        chunks.append(FileChunk(fid, off, ln, mtime=i + 1))

    for _ in range(50):
        off = rng.randrange(0, size - 1)
        ln = rng.randrange(1, size - off)
        got = bytearray(ln)
        for view in read_plan(chunks, off, ln):
            piece = data_by_fid[view.fid][
                view.offset_in_chunk:view.offset_in_chunk + view.size]
            got[view.logic_offset - off:
                view.logic_offset - off + view.size] = piece
        assert bytes(got) == bytes(truth[off:off + ln]), (off, ln)


def test_etag_aggregation():
    one = [FileChunk("1,a", 0, 10, etag="abcd")]
    assert etag(one) == "abcd"
    two = one + [FileChunk("2,b", 10, 10, etag="ef01")]
    assert etag(two).endswith("-2")


# ---------- stores ----------

@pytest.mark.parametrize("make_store", [
    lambda tmp: MemoryStore(),
    lambda tmp: SqliteStore(path=str(tmp / "filer.db")),
])
def test_store_contract(tmp_path, make_store):
    s = make_store(tmp_path)
    s.insert_entry(new_directory("/d"))
    for name in ["b.txt", "a.txt", "c.txt"]:
        s.insert_entry(new_file(f"/d/{name}"))
    assert s.find_entry("/d/a.txt") is not None
    assert s.find_entry("/d/zzz") is None

    names = [e.name for e in s.list_directory_entries("/d")]
    assert names == ["a.txt", "b.txt", "c.txt"]
    # pagination
    page = s.list_directory_entries("/d", start_file_name="a.txt",
                                    include_start=False, limit=1)
    assert [e.name for e in page] == ["b.txt"]
    # prefix
    assert [e.name for e in s.list_directory_entries("/d", prefix="c")] == \
        ["c.txt"]

    s.delete_entry("/d/b.txt")
    assert s.find_entry("/d/b.txt") is None

    s.insert_entry(new_directory("/d/sub"))
    s.insert_entry(new_file("/d/sub/x"))
    s.delete_folder_children("/d")
    assert s.find_entry("/d/sub/x") is None
    assert s.find_entry("/d") is not None  # the dir itself survives

    s.kv_put("k1", b"v1")
    assert s.kv_get("k1") == b"v1"
    assert s.kv_get("nope") is None
    s.close()


def test_sqlite_store_persistence(tmp_path):
    p = str(tmp_path / "f.db")
    s = SqliteStore(path=p)
    s.insert_entry(new_file("/a/b/c.txt",
                            [FileChunk("3,abc", 0, 42, etag="e")]))
    s.close()
    s2 = SqliteStore(path=p)
    e = s2.find_entry("/a/b/c.txt")
    assert e is not None and e.chunks[0].fid == "3,abc"
    assert e.chunks[0].size == 42
    s2.close()


# ---------- filer core ----------

def make_filer():
    deleted = []
    f = Filer(MemoryStore(), on_delete_chunks=deleted.extend)
    return f, deleted


def test_filer_create_with_parents():
    f, _ = make_filer()
    f.create_entry(new_file("/a/b/c/file.txt"))
    assert f.find_entry("/a").is_directory
    assert f.find_entry("/a/b/c").is_directory
    assert not f.find_entry("/a/b/c/file.txt").is_directory
    listing = f.list_directory("/a/b/c")
    assert [e.name for e in listing] == ["file.txt"]


def test_filer_recursive_delete_frees_chunks():
    f, deleted = make_filer()
    f.create_entry(new_file("/x/1", [FileChunk("1,a", 0, 10)]))
    f.create_entry(new_file("/x/sub/2", [FileChunk("2,b", 0, 20)]))
    with pytest.raises(OSError):
        f.delete_entry("/x", recursive=False)
    f.delete_entry("/x", recursive=True)
    assert f.find_entry("/x") is None
    assert {c.fid for c in deleted} == {"1,a", "2,b"}


def test_filer_rename_tree():
    f, _ = make_filer()
    f.create_entry(new_file("/src/d/f1", [FileChunk("1,a", 0, 5)]))
    f.create_entry(new_file("/src/f2"))
    f.rename("/src", "/dst")
    assert f.find_entry("/src") is None
    assert f.find_entry("/dst/d/f1").chunks[0].fid == "1,a"
    assert f.find_entry("/dst/f2") is not None


def test_filer_events():
    f, _ = make_filer()
    seen = []
    f.meta_log.subscribe(seen.append)
    f.create_entry(new_file("/ev/file"))
    f.delete_entry("/ev/file")
    kinds = [(e.old_entry is not None, e.new_entry is not None)
             for e in seen]
    # mkdir /ev, create file, delete file
    assert (False, True) in kinds and (True, False) in kinds
    assert f.meta_log.events_since(0, "/ev")


def test_filer_excl_and_type_conflicts():
    f, _ = make_filer()
    f.create_entry(new_file("/p/f"))
    with pytest.raises(FileExistsError):
        f.create_entry(new_file("/p/f"), o_excl=True)
    f.create_entry(new_directory("/p/d"))
    with pytest.raises(IsADirectoryError):
        f.create_entry(new_file("/p/d"))
    with pytest.raises(NotADirectoryError):
        f.create_entry(new_file("/p/f/under-file"))


def test_rename_rollback_on_failure(tmp_path):
    """A mid-rename store failure must leave the namespace unchanged
    (review regression: transaction hooks were no-ops)."""
    s = SqliteStore(path=str(tmp_path / "txn.db"))
    f = Filer(s)
    f.create_entry(new_file("/t/a/f1"))
    f.create_entry(new_file("/t/a/f2"))

    real_insert = s.insert_entry
    calls = {"n": 0}

    def failing_insert(entry):
        calls["n"] += 1
        if calls["n"] >= 2 and entry.full_path.startswith("/t/b"):
            raise sqlite3.OperationalError("disk I/O error (injected)")
        real_insert(entry)

    s.insert_entry = failing_insert
    with pytest.raises(sqlite3.OperationalError):
        f.rename("/t/a", "/t/b")
    s.insert_entry = real_insert
    # nothing moved, nothing lost
    assert f.find_entry("/t/a/f1") is not None
    assert f.find_entry("/t/a/f2") is not None
    assert f.find_entry("/t/b") is None
    s.close()


def test_sqlite_prefix_with_special_chars(tmp_path):
    s = SqliteStore(path=str(tmp_path / "p.db"))
    s.insert_entry(new_file("/d/my_file.txt"))
    s.insert_entry(new_file("/d/myXfile.txt"))
    s.insert_entry(new_file("/d/100%.txt"))
    # '_' must be literal, not a wildcard
    assert [e.name for e in s.list_directory_entries("/d", prefix="my_")] == \
        ["my_file.txt"]
    assert [e.name for e in s.list_directory_entries("/d", prefix="100%")] == \
        ["100%.txt"]
    s.close()
