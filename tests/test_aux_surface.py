"""Auxiliary surfaces: xattr + hardlinks on the mount, the fsspec
adapter, labeled metrics + status UI, and profiling endpoints.
"""

import json
import time
import urllib.request

import pytest

from cluster_util import Cluster


@pytest.fixture(scope="module")
def cluster():
    c = Cluster(n_volume_servers=1)
    yield c
    c.shutdown()


@pytest.fixture(scope="module")
def filer(cluster):
    fs = cluster.add_filer(chunk_size=8 * 1024)
    time.sleep(0.3)
    return fs


def test_wfs_xattr(cluster, filer):
    from seaweedfs_tpu.mount.wfs import WFS, FuseError
    w = WFS(filer.url)
    fh = w.create("/x/attrs.txt")
    w.write(fh, b"data", 0)
    w.flush(fh)
    w.release(fh)

    w.setxattr("/x/attrs.txt", "user.color", b"blue")
    w.setxattr("/x/attrs.txt", "user.shape", b"round")
    assert w.getxattr("/x/attrs.txt", "user.color") == b"blue"
    assert sorted(w.listxattr("/x/attrs.txt")) == ["user.color",
                                                   "user.shape"]
    w.removexattr("/x/attrs.txt", "user.color")
    assert w.listxattr("/x/attrs.txt") == ["user.shape"]
    with pytest.raises(FuseError):
        w.getxattr("/x/attrs.txt", "user.color")
    w.destroy()


def test_wfs_hardlink_shares_data_and_survives_unlink(cluster, filer):
    from seaweedfs_tpu.mount.wfs import WFS
    w = WFS(filer.url)
    fh = w.create("/h/orig.bin")
    w.write(fh, b"linked-bytes" * 100, 0)
    w.flush(fh)
    w.release(fh)

    w.link("/h/orig.bin", "/h/alias.bin")
    fh = w.open("/h/alias.bin")
    assert w.read(fh, 12, 0) == b"linked-bytes"
    w.release(fh)

    # removing the original must not free the shared chunks
    w.unlink("/h/orig.bin")
    time.sleep(0.5)  # let any (wrong) chunk deletion run
    fh = w.open("/h/alias.bin")
    assert w.read(fh, 12 * 100, 0) == b"linked-bytes" * 100
    w.release(fh)
    # removing the last link frees them
    w.unlink("/h/alias.bin")
    w.destroy()


def test_fsspec_adapter(cluster, filer):
    import fsspec

    from seaweedfs_tpu.integrations.fsspec_fs import register
    register()
    fs = fsspec.filesystem("seaweedfs", filer=filer.url)

    with fs.open(f"seaweedfs://{filer.url}/fsspec/data.txt", "wb") as f:
        f.write(b"fsspec payload")
    assert fs.cat_file("/fsspec/data.txt") == b"fsspec payload"
    assert fs.cat_file("/fsspec/data.txt", start=7, end=14) == b"payload"
    info = fs.info("/fsspec/data.txt")
    assert info["type"] == "file" and info["size"] == 14
    names = fs.ls("/fsspec", detail=False)
    assert "fsspec/data.txt" in names
    assert fs.exists("/fsspec/data.txt")
    fs.mv("/fsspec/data.txt", "/fsspec/moved.txt")
    assert not fs.exists("/fsspec/data.txt")
    with fsspec.open(f"seaweedfs://{filer.url}/fsspec/moved.txt") as f:
        assert f.read() == b"fsspec payload"
    fs.rm("/fsspec", recursive=True)
    assert not fs.exists("/fsspec/moved.txt")


def test_labeled_metrics_render():
    from seaweedfs_tpu.utils.metrics import Registry
    r = Registry("test")
    r.count("reads", labels={"collection": "photos"})
    r.count("reads", labels={"collection": "photos"})
    r.count("reads", labels={"collection": "docs"})
    r.count("reads")
    r.gauge("volumes", 3, labels={"collection": "photos", "type": "ec"})
    text = r.render()
    assert 'seaweedfs_tpu_test_reads_total{collection="photos"} 2.0' in text
    assert 'seaweedfs_tpu_test_reads_total{collection="docs"} 1.0' in text
    assert "seaweedfs_tpu_test_reads_total 1.0" in text
    assert ('seaweedfs_tpu_test_volumes{collection="photos",type="ec"} 3'
            in text)
    assert text.count("# TYPE seaweedfs_tpu_test_reads_total counter") == 1


def test_status_ui_and_profile_endpoints(cluster, filer):
    # write a blob first so the volume tables have rows
    cluster.client.upload(b"ui page blob", collection="")
    url = cluster.master_url.split(",")[0]
    with urllib.request.urlopen(f"http://{url}/ui", timeout=10) as r:
        page = r.read().decode()
    # real status page: cluster card + data-node/volume TABLES
    # (master_ui/templates.go parity, not a JSON dump)
    assert "master" in page and "<table" in page
    assert "data nodes" in page and "volumes" in page
    assert "raft term" in page
    vs_url = cluster.volume_servers[0].url
    with urllib.request.urlopen(f"http://{vs_url}/ui", timeout=10) as r:
        vpage = r.read().decode()
    assert "volume" in vpage and "<table" in vpage
    assert "disks" in vpage and "collection" in vpage
    with urllib.request.urlopen(f"http://{filer.url}/ui", timeout=10) as r:
        fpage = r.read().decode()
    assert "filer" in fpage and "root entries" in fpage
    with urllib.request.urlopen(
            f"http://{vs_url}/debug/profile?seconds=0.2", timeout=10) as r:
        assert "cumulative" in r.read().decode()


def test_vs_exports_labeled_volume_gauges(cluster, filer):
    c = cluster
    c.client.upload(b"gauge me", collection="")
    c.wait_heartbeats()
    vs_url = c.volume_servers[0].url
    with urllib.request.urlopen(f"http://{vs_url}/metrics",
                                timeout=10) as r:
        text = r.read().decode()
    assert 'volumes{collection="default"' in text


def test_s3_replication_sink(cluster, filer):
    from aiohttp import web

    from cluster_util import free_port
    from seaweedfs_tpu.filer.entry import new_directory, new_file
    from seaweedfs_tpu.replication.sink import S3Sink
    from seaweedfs_tpu.s3.s3_server import S3Server

    port = free_port()
    server = S3Server(filer.url)

    async def boot():
        runner = web.AppRunner(server.app)
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", port)
        await site.start()
        return runner

    cluster.runners.append(cluster.call(boot()))
    endpoint = f"http://127.0.0.1:{port}"
    urllib.request.urlopen(
        urllib.request.Request(f"{endpoint}/replbucket", method="PUT"),
        timeout=10).read()

    sink = S3Sink(endpoint, "replbucket", directory="/mirror")
    assert "replbucket" in sink.identity()
    entry = new_file("/site/index.html", [])
    sink.create_entry(entry, lambda: b"<h1>replicated</h1>")
    with urllib.request.urlopen(
            f"{endpoint}/replbucket/mirror/site/index.html",
            timeout=10) as r:
        assert r.read() == b"<h1>replicated</h1>"
    sink.create_entry(new_directory("/site/sub"), lambda: b"")  # no-op
    sink.delete_entry(entry)
    with pytest.raises(urllib.error.HTTPError):
        urllib.request.urlopen(
            f"{endpoint}/replbucket/mirror/site/index.html", timeout=10)
    sink.delete_entry(entry)  # idempotent: 404 swallowed


def test_webhook_notification_queue(tmp_path):
    import http.server
    import threading as threading_mod

    from seaweedfs_tpu.notification.queues import WebhookQueue

    received = []

    class Handler(http.server.BaseHTTPRequestHandler):
        def do_POST(self):
            n = int(self.headers.get("Content-Length", 0))
            received.append(json.loads(self.rfile.read(n)))
            self.send_response(204)
            self.end_headers()

        def log_message(self, *a):
            pass

    srv = http.server.HTTPServer(("127.0.0.1", 0), Handler)
    threading_mod.Thread(target=srv.serve_forever, daemon=True).start()
    url = f"http://127.0.0.1:{srv.server_port}/hook"

    class Ev:
        def to_dict(self):
            return {"directory": "/x", "tsns": 1}

    spool = tmp_path / "spool.ndjson"
    q = WebhookQueue(url, spool_path=str(spool), timeout=3)
    q.notify(Ev())
    deadline = time.time() + 5
    while time.time() < deadline and not received:
        time.sleep(0.05)
    assert received and received[0]["directory"] == "/x"
    srv.shutdown()

    # endpoint down: event lands in the spool, notify() never blocks
    t0 = time.time()
    q.notify(Ev())
    assert time.time() - t0 < 0.5
    deadline = time.time() + 8
    while time.time() < deadline and not spool.exists():
        time.sleep(0.1)
    assert spool.exists() and "/x" in spool.read_text()


def test_kv_sequencer_unique_across_instances():
    """KvSequencer (etcd_sequencer.go role): two masters leasing key
    ranges from one shared atomic counter never mint the same id."""
    from seaweedfs_tpu.filer.fake_redis import FakeRedisServer
    from seaweedfs_tpu.topology.sequence import KvSequencer

    with FakeRedisServer() as (host, port):
        a = KvSequencer(host, port, batch=10)
        b = KvSequencer(host, port, batch=10)
        seen = set()
        for _ in range(100):
            first = a.next_file_id(3)
            seen.update(range(first, first + 3))
            other = b.next_file_id(2)
            seen.update(range(other, other + 2))
        assert len(seen) == 500  # all unique across both sequencers

        # set_max pushes the shared counter past the observed key, so
        # every FUTURE lease (any instance) mints above it; the current
        # leases stay valid (disjoint ranges are unique regardless)
        a.set_max(10_000)
        for _ in range(30):  # exhaust both stale leases
            last_a = a.next_file_id()
            last_b = b.next_file_id()
        assert last_a > 10_000 and last_b > 10_000
        assert last_a != last_b
