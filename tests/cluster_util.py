"""In-process test cluster: master + N volume servers on localhost ports.

The asyncio servers run on a dedicated background loop thread; tests drive
them synchronously through the Client — the same pattern as the reference's
out-of-tree live-cluster tests (test/s3/basic), but in-process and CI-safe.
"""

from __future__ import annotations

import asyncio
import socket
import tempfile
import threading
import time

from seaweedfs_tpu.client import Client
from seaweedfs_tpu.ec.geometry import Geometry
from seaweedfs_tpu.server.master import MasterServer
from seaweedfs_tpu.server.volume_server import VolumeServer
from seaweedfs_tpu.storage.store import Store

TEST_GEOMETRY = Geometry(10, 4, large_block_size=64 * 1024,
                         small_block_size=4 * 1024)


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def free_port_with_grpc_twin() -> int:
    """A free HTTP port whose +10000 twin (the production gRPC
    convention, grpc_client_server.go) is also free and <= 65535 — peers
    derive each other's gRPC target from the HTTP url, so tests must
    honor the convention."""
    for _ in range(64):
        port = free_port()
        if port + 10000 > 65535:
            continue
        with socket.socket() as s:
            try:
                s.bind(("127.0.0.1", port + 10000))
            except OSError:
                continue
            return port
    raise RuntimeError("no free port pair found")


class Cluster:
    def __init__(self, n_volume_servers: int = 3,
                 geometry: Geometry = TEST_GEOMETRY,
                 coder_name: str = "numpy",
                 default_replication: str = "000",
                 max_volumes: int = 16,
                 pulse: float = 0.15,
                 n_masters: int = 1,
                 master_grpc_port: int = 0,
                 master_kwargs: dict | None = None):
        self.geometry = geometry
        self.coder_name = coder_name
        self.default_replication = default_replication
        self.max_volumes = max_volumes
        self.pulse = pulse
        self.n = n_volume_servers
        self.n_masters = n_masters
        self.master_grpc_port = master_grpc_port
        self.master_kwargs = master_kwargs or {}

        self.loop = asyncio.new_event_loop()
        self.thread = threading.Thread(target=self._loop_main, daemon=True)
        self.thread.start()
        self.tmpdirs: list[tempfile.TemporaryDirectory] = []
        self.volume_servers: list[VolumeServer] = []
        self.runners: list = []
        self._vs_runners: list = []
        self._start()

    def _loop_main(self) -> None:
        asyncio.set_event_loop(self.loop)
        try:
            self.loop.run_forever()
        finally:
            # Drain on THIS thread instead of abandoning pending tasks
            # (parked meta-subscribe handlers, watcher Event.waits) to
            # interpreter-exit GC: a coroutine finalized at shutdown
            # runs its finally-blocks (unsubscribe -> lock acquire) in
            # GC context, which both spams "Task was destroyed but it
            # is pending!" and can deadlock against a frozen daemon
            # thread — weedsan's task tracker flags exactly this.
            try:
                tasks = asyncio.all_tasks(self.loop)
                for t in tasks:
                    t.cancel()
                if tasks:
                    self.loop.run_until_complete(asyncio.gather(
                        *tasks, return_exceptions=True))
                self.loop.run_until_complete(
                    self.loop.shutdown_asyncgens())
            except Exception:
                pass
            self.loop.close()

    def call(self, coro, timeout: float = 60.0):
        return asyncio.run_coroutine_threadsafe(coro, self.loop) \
            .result(timeout)

    def serve(self, app, port: int):
        """Boot an aiohttp app on the background loop; returns its runner.
        All runner bookkeeping goes through here so indices stay coherent."""
        from aiohttp import web

        async def boot():
            # short shutdown timeout: streaming handlers (meta subscribe,
            # tail) may be parked on a queue and must not stall teardown
            runner = web.AppRunner(app, shutdown_timeout=1.0)
            await runner.setup()
            site = web.TCPSite(runner, "127.0.0.1", port)
            await site.start()
            return runner

        return self.call(boot())

    def _start(self) -> None:
        from aiohttp import web

        master_ports = [free_port() for _ in range(self.n_masters)]
        master_urls = [f"127.0.0.1:{p}" for p in master_ports]
        self.masters: list[MasterServer] = []
        self._master_runners: list = []
        for i, (port, url) in enumerate(zip(master_ports, master_urls)):
            m = MasterServer(
                volume_size_limit_mb=1,  # tiny: volumes seal quickly
                default_replication=self.default_replication,
                pulse_seconds=self.pulse,
                url=url,
                peers=master_urls if self.n_masters > 1 else None,
                election_timeout=(0.15, 0.3),
                raft_heartbeat=0.05,
                grpc_port=self.master_grpc_port if i == 0 else 0,
                **self.master_kwargs)
            runner = self.serve(m.app, port)
            self.masters.append(m)
            self._master_runners.append(runner)
            self.runners.append(runner)
        self.master = self.masters[0]
        self.master_port = master_ports[0]
        self.master_url = ",".join(master_urls)
        if self.n_masters > 1:
            self.wait_for_leader()

        for i in range(self.n):
            self.add_volume_server()
        self.wait_for_nodes(self.n)
        self.client = Client(self.master_url)

    def wait_for_leader(self, timeout: float = 10.0) -> "MasterServer":
        deadline = time.time() + timeout
        while time.time() < deadline:
            for m in self.masters:
                if m.raft.is_leader:
                    return m
            time.sleep(0.05)
        raise TimeoutError("no master elected leader")

    def stop_master(self, index: int) -> None:
        runner = self._master_runners[index]
        m = self.masters[index]

        async def halt():
            await m.raft.stop()
            await runner.cleanup()

        self.call(halt())

    def add_volume_server(self, data_center: str = "dc1",
                          rack: str = "",
                          use_grpc_heartbeat: bool = False,
                          with_grpc: bool = False) -> VolumeServer:
        from aiohttp import web

        tmp = tempfile.TemporaryDirectory(prefix="weedtpu_vs_")
        self.tmpdirs.append(tmp)
        port = free_port_with_grpc_twin() if with_grpc else free_port()
        store = Store([tmp.name], max_volume_counts=[self.max_volumes],
                      coder_name=self.coder_name, geometry=self.geometry)
        vs = VolumeServer(store, self.master_url, url=f"127.0.0.1:{port}",
                          data_center=data_center,
                          rack=rack or f"rack{len(self.volume_servers) % 2}",
                          pulse_seconds=self.pulse,
                          use_grpc_heartbeat=use_grpc_heartbeat,
                          grpc_port=port + 10000 if with_grpc else 0,
                          master_grpc_target=(
                              f"127.0.0.1:{self.master_grpc_port}"
                              if use_grpc_heartbeat else ""))

        runner = self.serve(vs.app, port)
        self.runners.append(runner)
        self._vs_runners.append(runner)
        self.volume_servers.append(vs)
        return vs

    def add_filer(self, store_name: str = "memory",
                  chunk_size: int = 16 * 1024,
                  with_grpc: bool = False,
                  store_kwargs: dict | None = None,
                  port: int = 0,
                  ring_peers: list[str] | None = None,
                  ring_replicas: int = 2):
        from aiohttp import web

        from seaweedfs_tpu.server.filer_server import FilerServer

        if not port:
            port = free_port_with_grpc_twin() if with_grpc else free_port()
        ring_config = None
        if ring_peers:
            from seaweedfs_tpu.metaring import RingConfig
            ring_config = RingConfig(peers=list(ring_peers),
                                     replicas=ring_replicas)
        fs = FilerServer(self.master_url, store_name=store_name,
                         store_kwargs=store_kwargs,
                         chunk_size=chunk_size,
                         url=f"127.0.0.1:{port}",
                         ring_config=ring_config,
                         grpc_port=port + 10000 if with_grpc else 0)

        runner = self.serve(fs.app, port)
        self.runners.append(runner)
        if not hasattr(self, "_filer_runners"):
            self._filer_runners = {}
        self._filer_runners[id(fs)] = runner
        fs.url = f"127.0.0.1:{port}"
        return fs

    def stop_filer(self, fs) -> None:
        """Kill one filer (chaos: the metaring peer-loss drills)."""
        runner = self._filer_runners.pop(id(fs))

        async def halt():
            await runner.cleanup()

        self.call(halt())
        self.runners.remove(runner)

    def stop_volume_server(self, index: int) -> None:
        vs = self.volume_servers[index]
        runner = self._vs_runners[index]

        async def halt():
            if vs._hb_task:
                vs._hb_task.cancel()
            await runner.cleanup()

        self.call(halt())

    def wait_for_nodes(self, n: int, timeout: float = 10.0) -> None:
        import json
        import urllib.request
        urls = self.master_url.split(",")
        deadline = time.time() + timeout
        while time.time() < deadline:
            for u in urls:
                try:
                    with urllib.request.urlopen(
                            f"http://{u}/dir/status", timeout=2) as r:
                        if len(json.load(r).get("nodes", [])) >= n:
                            return
                except Exception:
                    pass
            time.sleep(0.05)
        raise TimeoutError(f"cluster did not reach {n} nodes")

    def wait_heartbeats(self) -> None:
        """Wait one full heartbeat round so the master sees current state."""
        time.sleep(self.pulse * 2 + 0.1)

    def shutdown(self) -> None:
        async def halt_all():
            for vs in self.volume_servers:
                if vs._hb_task:
                    vs._hb_task.cancel()
            for runner in self.runners:
                try:
                    await runner.cleanup()
                except Exception:
                    pass

        try:
            self.call(halt_all(), timeout=20)
        finally:
            self.loop.call_soon_threadsafe(self.loop.stop)
            self.thread.join(timeout=5)
            for tmp in self.tmpdirs:
                try:
                    tmp.cleanup()
                except Exception:
                    pass
