"""One-pass warm-down (ec/fused.py): byte-identity against the
sequential vacuum -> gzip -> encode chain, the gated incremental
layout, fail-closed fault handling, the store promote, and the
governor's gzip-worker axis.

The identity tests are the contract that lets the fused pass replace
the chained path everywhere: the compacted .dat/.idx, the sorted .ecx,
every shard file and the .ecm digests must match what the serial
pipeline produces, byte for byte, across geometries, odd needle sizes,
gzip-declined payloads, zero-live volumes and multi-worker pools.
"""

import json
import os
import threading

import numpy as np
import pytest

from seaweedfs_tpu import faults
from seaweedfs_tpu.ec import get_coder, striping
from seaweedfs_tpu.ec import governor
from seaweedfs_tpu.ec.fused import (_Watermark, _gated_segments,
                                    fused_vacuum_gzip_encode)
from seaweedfs_tpu.ec.geometry import Geometry, to_ext
from seaweedfs_tpu.ec.pipeline import (read_stamped_digests,
                                       shard_file_digest, stream_encode)
from seaweedfs_tpu.storage import idx as idx_mod
from seaweedfs_tpu.storage import types as t
from seaweedfs_tpu.storage.needle import (FLAG_HAS_MIME, FLAG_HAS_NAME,
                                          FLAG_IS_COMPRESSED, Needle)
from seaweedfs_tpu.storage.superblock import SuperBlock
from seaweedfs_tpu.storage.volume import Volume
from seaweedfs_tpu.utils import compression


@pytest.fixture(autouse=True)
def _clean_slate():
    faults.clear()
    governor.reset()
    yield
    faults.clear()
    governor.reset()


# ------------------------------------------------ the serial reference

def sequential_reference(volume, dst_base, coder, g, gzip_level=1):
    """The chained path the fused pass replaces: compact + gzip into
    dst via per-needle reads, then stream_encode, then the sorted .ecx
    — reimplemented here (not imported) so a regression in EITHER path
    breaks the comparison instead of moving both sides."""
    with volume._lock:
        snapshot = [nv for nv in volume.nm.values()
                    if t.size_is_valid(nv.size)]
        sb = SuperBlock(
            version=volume.super_block.version,
            replica_placement=volume.super_block.replica_placement,
            ttl=volume.super_block.ttl,
            compaction_revision=(volume.super_block.compaction_revision
                                 + 1),
            extra=volume.super_block.extra)
    snapshot.sort(key=lambda nv: nv.offset)
    with open(dst_base + ".dat", "wb") as dat, \
            open(dst_base + ".idx", "wb") as idx:
        dat.write(sb.to_bytes())
        offset = len(sb.to_bytes())
        for nv in snapshot:
            n = volume.read_needle_at(t.stored_to_offset(nv.offset),
                                      nv.size)
            if n.data and not n.is_compressed \
                    and volume.version != t.VERSION1:
                head = n.data[:4096]
                trial = compression.compress(head, level=gzip_level)
                if len(trial) * 10 < len(head) * 9:
                    comp = compression.compress(n.data, level=gzip_level)
                    if len(comp) * 10 < len(n.data) * 9:
                        n.data = comp
                        n.set_flag(FLAG_IS_COMPRESSED)
            record = n.to_bytes(volume.version)
            if offset % t.NEEDLE_PADDING_SIZE:
                pad = (-offset) % t.NEEDLE_PADDING_SIZE
                dat.write(bytes(pad))
                offset += pad
            dat.write(record)
            idx.write(idx_mod.pack_entry(
                nv.key, t.offset_to_stored(offset, volume.offset_size),
                n.size, offset_size=volume.offset_size))
            offset += len(record)
    stream_encode(dst_base, coder, g)
    striping.write_sorted_ecx_from_idx(
        dst_base, offset_size=volume.offset_size)


def build_volume(d, vid, n_needles, rng):
    """Five payload kinds (compressible / gzip-declined random / tiny
    odd sizes / pre-compressed / bulky), names+mimes on every third,
    half the ids tombstoned — the full splice surface."""
    v = Volume(d, "", vid, create=True)
    for i in range(n_needles):
        kind = i % 5
        if kind == 0:
            data = b"compressible text block " * int(rng.integers(1, 400))
        elif kind == 1:
            data = rng.integers(0, 256, size=int(rng.integers(1, 9000)),
                                dtype=np.uint8).tobytes()
        elif kind == 2:
            data = b"x" * int(rng.integers(1, 7))
        elif kind == 3:
            data = compression.compress(b"already " * 600)
        else:
            data = b"padme" * int(rng.integers(100, 5000))
        n = Needle(cookie=int(rng.integers(0, 2**32)), id=i + 1,
                   data=data)
        if kind == 3:
            n.set_flag(FLAG_IS_COMPRESSED)
        if i % 3 == 0:
            n.name = f"file-{i}.txt".encode()
            n.mime = b"text/plain"
            n.set_flag(FLAG_HAS_NAME)
            n.set_flag(FLAG_HAS_MIME)
        v.write_needle(n)
    for i in range(n_needles):
        if i % 4 in (1, 2):
            v.delete_needle(Needle(cookie=0, id=i + 1))
    return v


def assert_identical(base_seq, base_fused, g):
    for ext in [".dat", ".idx", ".ecx"] + [to_ext(i)
                                           for i in range(g.total_shards)]:
        with open(base_seq + ext, "rb") as fa, \
                open(base_fused + ext, "rb") as fb:
            a, b = fa.read(), fb.read()
        if a != b:
            common = min(len(a), len(b))
            first = next((i for i in range(common) if a[i] != b[i]),
                         common)
            pytest.fail(f"{ext}: fused diverges from sequential at "
                        f"byte {first} (sizes {len(a)} vs {len(b)})")
    # the scrubber's first verification rides the pass: the fused .ecm
    # carries a digest for EVERY shard and they equal the true file
    # digests — no host re-digest is ever needed for a fused volume
    stamped = read_stamped_digests(base_fused)
    true = shard_file_digest(base_fused, range(g.total_shards))
    assert set(stamped) == set(range(g.total_shards))
    for i in range(g.total_shards):
        assert stamped[i] == int(true[i])
    with open(base_seq + ".ecm") as fa, open(base_fused + ".ecm") as fb:
        assert json.load(fa)["dat_size"] == json.load(fb)["dat_size"]


@pytest.mark.parametrize("kmbb,needles,seed", [
    ((10, 4, 64 * 1024, 4 * 1024), 120, 1),
    ((20, 4, 32 * 1024, 2 * 1024), 90, 2),
], ids=["rs10+4", "rs20+4"])
def test_fused_identity(tmp_path, kmbb, needles, seed):
    k, m, lb, sb = kmbb
    g = Geometry(k, m, lb, sb)
    coder = get_coder("numpy", k, m)
    v = build_volume(str(tmp_path), 7, needles, np.random.default_rng(seed))
    stats = fused_vacuum_gzip_encode(v, str(tmp_path / "fused"), coder, g)
    sequential_reference(v, str(tmp_path / "seq"), coder, g)
    assert_identical(str(tmp_path / "seq"), str(tmp_path / "fused"), g)
    assert stats["gzipped_needles"] > 0       # the splice actually ran
    assert stats["live_needles"] < needles    # tombstones actually left
    v.close()


def test_fused_identity_zero_live(tmp_path):
    """Every needle deleted: the fused pass still emits a valid (header
    -only) volume + full shard set, identical to the serial path."""
    g = Geometry(10, 4, 64 * 1024, 4 * 1024)
    coder = get_coder("numpy", 10, 4)
    v = build_volume(str(tmp_path), 7, 40, np.random.default_rng(3))
    for i in range(40):
        v.delete_needle(Needle(cookie=0, id=i + 1))
    stats = fused_vacuum_gzip_encode(v, str(tmp_path / "fused"), coder, g)
    sequential_reference(v, str(tmp_path / "seq"), coder, g)
    assert_identical(str(tmp_path / "seq"), str(tmp_path / "fused"), g)
    assert stats["live_needles"] == 0
    v.close()


def test_fused_identity_governed_multiworker(tmp_path, monkeypatch):
    """Multi-worker pools (parallel chunk jobs, strictly-ordered yield)
    must not reorder a single output byte."""
    monkeypatch.setenv("WEED_EC_GZIP_WORKERS", "4")
    monkeypatch.setenv("WEED_EC_GZIP_MAX", "8")   # 1-core boxes clamp
    monkeypatch.setenv("WEED_EC_READERS", "3")
    governor.reset()
    g = Geometry(10, 4, 64 * 1024, 4 * 1024)
    coder = get_coder("numpy", 10, 4)
    v = build_volume(str(tmp_path), 7, 120, np.random.default_rng(5))
    stats = fused_vacuum_gzip_encode(v, str(tmp_path / "fused"), coder, g)
    sequential_reference(v, str(tmp_path / "seq"), coder, g)
    assert_identical(str(tmp_path / "seq"), str(tmp_path / "fused"), g)
    assert stats["gzip_workers"] == 4
    v.close()


# ---------------------------------------------- gated incremental layout

def _finished_wm(total):
    wm = _Watermark()
    wm.advance(total)
    wm.finish(total)
    return wm


@pytest.mark.parametrize("total", [
    0, 1, 4095, 4096 * 10 - 1, 4096 * 10, 4096 * 10 + 1,
    65536 * 10 - 4096, 65536 * 10, 65536 * 10 + 4096 * 3,
    65536 * 10 + 65536 * 10 - 4096 * 10 + 1,   # the ambiguity window
    65536 * 25 + 1234,
])
def test_gated_segments_match_stripe_segments(total):
    """With the watermark already final, the gated generator must be
    segment-for-segment identical to the offline layout for every tail
    shape — including the pad-a-large-row ambiguity window."""
    g = Geometry(10, 4, 65536, 4096)
    got = list(_gated_segments(g, 4096 * 4, _finished_wm(total)))
    want = list(striping.stripe_segments(total, g, 4096 * 4))
    assert got == want


def test_gated_segments_stream_before_total_is_known():
    """The overlap property itself: once the flushed watermark proves
    the remainder exceeds the large/small threshold, segments yield
    WITHOUT waiting for the compactor to finish."""
    g = Geometry(3, 2, 8192, 1024)
    wm = _Watermark()
    seg_iter = _gated_segments(g, 1024, wm)
    got = []
    grabber = threading.Thread(
        target=lambda: got.extend([next(seg_iter), next(seg_iter)]))
    # flushed far past (large_row - small_row) + the first segments'
    # cover: the first large-row segments must yield while the total
    # is still unknown
    wm.advance(g.large_row_size + g.small_row_size)
    grabber.start()
    grabber.join(timeout=10)
    assert not grabber.is_alive(), \
        "gated segments did not stream ahead of the compactor"
    total = g.large_row_size + g.small_row_size  # now finish and drain
    wm.finish(total)
    rest = list(seg_iter)
    assert got + rest == list(striping.stripe_segments(total, g, 1024))


def test_watermark_fail_propagates():
    wm = _Watermark()
    wm.fail(ValueError("boom"))
    with pytest.raises(RuntimeError):
        wm.wait_cover(10)


# ------------------------------------------------- fail-closed fault paths

def _assert_no_dst(base, g):
    leftovers = [base + ext for ext in
                 [".dat", ".idx", ".ecx", ".ecm"]
                 + [to_ext(i) for i in range(g.total_shards)]
                 if os.path.exists(base + ext)]
    assert not leftovers, f"partial dst files left behind: {leftovers}"


@pytest.mark.parametrize("point", ["ec.fused.read", "ec.fused.gzip",
                                   "ec.fused.commit"])
def test_fused_fault_fails_closed(tmp_path, point):
    """A drop armed at any fused fault point aborts the pass AND
    removes every partial dst file — the source volume stays the only
    copy, exactly the crash-consistency dual-state contract."""
    g = Geometry(3, 2, 8192, 1024)
    coder = get_coder("numpy", 3, 2)
    v = build_volume(str(tmp_path), 7, 30, np.random.default_rng(9))
    base = str(tmp_path / "fused")
    faults.set_fault(point, "drop")
    with pytest.raises((RuntimeError, OSError)):
        fused_vacuum_gzip_encode(v, base, coder, g)
    _assert_no_dst(base, g)
    faults.clear()
    # the source is untouched: the same call now succeeds end to end
    fused_vacuum_gzip_encode(v, base, coder, g)
    assert os.path.exists(base + ".ecm")
    v.close()


# ------------------------------------------------------ store-level flow

def test_store_fused_generate_promotes_atomically(tmp_path):
    from seaweedfs_tpu.ec.geometry import GeometryPolicy
    from seaweedfs_tpu.storage.store import Store

    policy = GeometryPolicy.parse("arc=3+2")
    store = Store([str(tmp_path)], coder_name="numpy",
                  geometry_policy=policy)
    vid = 7
    store.add_volume(vid, collection="arc")
    for i in range(12):
        data = (b"store fused text " * 40) if i % 2 else os.urandom(900)
        store.write_needle(vid, Needle(id=i + 1, cookie=1, data=data))
    store.delete_needle(vid, Needle(id=3, cookie=1))
    base = store.find_volume(vid).base_file_name()
    # stale staging junk from a "crashed" earlier pass must be swept
    with open(base + ".fusing.dat", "wb") as f:
        f.write(b"stale")
    shards = store.ec_fused_generate(vid)
    assert shards == list(range(5))
    for sid in range(5):
        assert os.path.exists(base + to_ext(sid))
    assert os.path.exists(base + ".ecx")
    assert os.path.exists(base + ".ecm")
    # nothing staging-named survives a successful promote
    assert not any(name.startswith("7.fusing")
                   for name in os.listdir(str(tmp_path)))
    # the SOURCE volume files are untouched (verify-then-retire: the
    # lifecycle daemon retires them only after mounted-shard verify)
    assert os.path.exists(base + ".dat")
    assert os.path.exists(base + ".idx")
    # digests stamped in the same commit: scrubber re-digest count 0
    stamped = read_stamped_digests(base)
    true = shard_file_digest(base, range(5))
    assert all(stamped[i] == int(true[i]) for i in range(5))


# ------------------------------------------------- governor gzip axis

def test_governor_widens_gzip_workers_when_gzip_bound(monkeypatch):
    from seaweedfs_tpu import observe
    monkeypatch.setenv("WEED_EC_GZIP_WORKERS", "1")
    monkeypatch.setenv("WEED_EC_GZIP_MAX", "8")
    gov = governor.FeedGovernor()
    assert gov.plan(100 * 1024 * 1024, 10).gzip_workers == 1
    ctx = observe.TraceCtx(observe.new_id(), "", "ec", "")
    for name, secs in (("ec.read", 0.1), ("ec.dispatch", 0.1),
                       ("ec.kernel", 0.1), ("ec.write", 0.1),
                       ("ec.compact", 0.4), ("ec.gzip", 5.0)):
        for _ in range(8):
            observe.record_span(name, ctx, 0, int(secs / 8 * 1e6))
    op = gov.plan(100 * 1024 * 1024, 10)
    gov.finish_run(ctx.trace_id, op, 100 * 1024 * 1024, 10)
    assert gov.plan(100 * 1024 * 1024, 10).gzip_workers == 2
