"""Compression (weed/util/compression.go), AES-256-GCM cipher
(weed/util/cipher.go), and the fused compact+gzip+RS pipeline (BASELINE
config 5)."""

import gzip
import os
import random
import urllib.request

import pytest

from cluster_util import Cluster, TEST_GEOMETRY
from seaweedfs_tpu.utils import cipher, compression


def test_compression_decision_table():
    assert compression.is_compressable(".txt", "")
    assert compression.is_compressable("", "text/html")
    assert compression.is_compressable("", "application/json")
    assert not compression.is_compressable(".jpg", "")
    assert not compression.is_compressable("", "image/png")
    assert not compression.is_compressable(".zip", "application/zip")


def test_compress_roundtrip_and_detection():
    data = b"the quick brown fox " * 200
    comp = compression.compress(data)
    assert compression.is_gzipped(comp)
    assert not compression.is_gzipped(data)
    assert compression.decompress(comp) == data
    out, did = compression.maybe_compress(data, ".txt", "")
    assert did and len(out) < len(data)
    rnd = os.urandom(4096)
    out, did = compression.maybe_compress(rnd, ".txt", "")
    assert not did and out is rnd  # incompressible stays raw


_needs_cipher = pytest.mark.skipif(
    not cipher.HAVE_AESGCM,
    reason="cryptography package not installed on this host")


@_needs_cipher
def test_cipher_roundtrip_and_tamper():
    data = os.urandom(10000)
    ct, key = cipher.encrypt(data)
    assert ct != data and len(ct) == len(data) + cipher.NONCE_SIZE + 16
    assert cipher.decrypt(ct, key) == data
    k2 = cipher.key_from_str(cipher.key_to_str(key))
    assert cipher.decrypt(ct, k2) == data
    bad = bytearray(ct)
    bad[20] ^= 0xFF
    with pytest.raises(Exception):
        cipher.decrypt(bytes(bad), key)


@pytest.fixture(scope="module")
def cluster():
    c = Cluster(n_volume_servers=1)
    yield c
    c.shutdown()


def test_volume_server_compresses_text(cluster):
    c = cluster
    text = b"compress me please " * 500
    fid = c.client.upload(text, filename="doc.txt", mime="text/plain")
    # stored form is gzip (flag set): check via the store directly
    vs = c.volume_servers[0]
    vid = int(fid.split(",")[0])
    key = int(fid.split(",")[1][:-8], 16)
    n = vs.store.read_needle(vid, key)
    assert n.is_compressed
    assert len(n.data) < len(text)
    assert gzip.decompress(n.data) == text
    # plain client (no Accept-Encoding) gets the original bytes back
    assert c.client.download(fid) == text
    # gzip-accepting client gets the compressed form verbatim
    url = c.client.lookup(vid)[0]
    req = urllib.request.Request(f"http://{url}/{fid}",
                                 headers={"Accept-Encoding": "gzip"})
    with urllib.request.urlopen(req, timeout=10) as r:
        assert r.headers.get("Content-Encoding") == "gzip"
        assert gzip.decompress(r.read()) == text


def test_volume_server_skips_binary(cluster):
    c = cluster
    blob = os.urandom(4000)
    fid = c.client.upload(blob, filename="x.jpg", mime="image/jpeg")
    vs = c.volume_servers[0]
    n = vs.store.read_needle(int(fid.split(",")[0]),
                             int(fid.split(",")[1][:-8], 16))
    assert not n.is_compressed
    assert c.client.download(fid) == blob


def test_compressed_replication_consistent():
    # the test cluster alternates racks, so "other rack, same DC" fits
    c = Cluster(n_volume_servers=2, default_replication="010")
    try:
        text = b"replicate compressed " * 400
        fid = c.client.upload(text, filename="r.txt", mime="text/plain")
        vid = int(fid.split(",")[0])
        key = int(fid.split(",")[1][:-8], 16)
        c.wait_heartbeats()
        seen = 0
        for vs in c.volume_servers:
            v = vs.store.find_volume(vid)
            if v is None:
                continue
            n = vs.store.read_needle(vid, key)
            assert n.is_compressed, vs.url
            assert gzip.decompress(n.data) == text
            seen += 1
        assert seen == 2
    finally:
        c.shutdown()


@_needs_cipher
def test_filer_cipher_end_to_end():
    c = Cluster(n_volume_servers=1)
    try:
        fs = c.add_filer()
        fs.cipher = True
        body = b"secret contents " * 1000
        urllib.request.urlopen(
            urllib.request.Request(f"http://{fs.url}/enc/file.bin",
                                   data=body, method="PUT"),
            timeout=10).read()
        # chunk metadata carries keys; volume stores only ciphertext
        entry = fs.filer.find_entry("/enc/file.bin")
        assert entry.chunks and all(ch.cipher_key for ch in entry.chunks)
        vs = c.volume_servers[0]
        for ch in entry.chunks:
            vid = int(ch.fid.split(",")[0])
            key = int(ch.fid.split(",")[1][:-8], 16)
            stored = vs.store.read_needle(vid, key).data
            assert body[:64] not in stored
        # full read and ranged read decrypt transparently
        with urllib.request.urlopen(f"http://{fs.url}/enc/file.bin",
                                    timeout=10) as r:
            assert r.read() == body
        req = urllib.request.Request(
            f"http://{fs.url}/enc/file.bin",
            headers={"Range": "bytes=17-48"})
        with urllib.request.urlopen(req, timeout=10) as r:
            assert r.read() == body[17:49]
    finally:
        c.shutdown()


def test_fused_vacuum_gzip_encode(tmp_path):
    from seaweedfs_tpu import ec
    from seaweedfs_tpu.ec.fused import fused_vacuum_gzip_encode
    from seaweedfs_tpu.storage.needle import Needle
    from seaweedfs_tpu.storage.volume import Volume

    rng = random.Random(5)
    v = Volume(str(tmp_path), "", 1, create=True)
    payloads = {}
    for i in range(1, 61):
        data = (b"fused pipeline text %d " % i) * rng.randint(5, 60)
        payloads[i] = data
        v.write_needle(Needle(cookie=0x500 + i, id=i, data=data))
    for i in range(1, 61, 2):  # delete odd ids -> ~half garbage
        v.delete_needle(Needle(cookie=0x500 + i, id=i))
        del payloads[i]

    coder = ec.get_coder("jax", 10, 4)
    geo = ec.Geometry(10, 4, large_block_size=10000, small_block_size=100)
    dst = str(tmp_path / "fused_1")
    out = fused_vacuum_gzip_encode(v, dst, coder, geo)
    assert out["live_needles"] == 30
    assert out["compacted_bytes"] < out["src_bytes"]
    for i in range(14):
        assert os.path.exists(dst + ec.to_ext(i))
    assert os.path.exists(dst + ".ecx")

    # decode the shards back and verify every live needle, decompressed
    dec_dir = tmp_path / "dec"
    dec_dir.mkdir()
    dec = str(dec_dir / "fused_1")
    import shutil
    for i in range(10):
        shutil.copy(dst + ec.to_ext(i), dec + ec.to_ext(i))
    shutil.copy(dst + ".ecx", dec + ".ecx")
    ec.write_dat_file(dec, os.path.getsize(dst + ".dat"), geo)
    ec.write_idx_file_from_ec_index(dec)
    v2 = Volume(str(dec_dir), "fused", 1)
    for i, data in payloads.items():
        n = v2.read_needle(i)
        assert n.is_compressed
        assert gzip.decompress(n.data) == data
    v.close()
    v2.close()
