"""Degraded-read hardening: tiered shard-location cache + failure
injection (a shard holder dies between reads).

Mirrors the reference's store_ec read path: cached shard locations with
freshness tiers (store_ec.go:221-262), parallel survivor fetch for online
reconstruction (store_ec.go:322-376), and reads that survive shard-holder
loss without polling the master per interval.
"""

import time

import pytest

from cluster_util import Cluster, TEST_GEOMETRY
from seaweedfs_tpu.shell.ec_commands import EcCommands


@pytest.fixture()
def cluster():
    c = Cluster(n_volume_servers=4)
    yield c
    c.shutdown()


def _setup_ec(c, n_files=12, size=3000):
    fids = {}
    for i in range(n_files):
        data = bytes([i % 251]) * size
        fid = c.client.upload(data, collection="deg")
        fids[fid] = data
    c.wait_heartbeats()
    vid = int(next(iter(fids)).split(",")[0])
    shell = EcCommands(c.client, TEST_GEOMETRY)
    shell.encode(vid, "deg", apply=True)
    c.wait_heartbeats()
    return vid, fids


def test_shard_location_cache_tiers(cluster):
    c = cluster
    vid, fids = _setup_ec(c)
    vs = c.volume_servers[0]

    # prime the cache through a few reads
    c.client._vid_cache.clear()
    for fid, data in list(fids.items())[:3]:
        assert c.client.download(fid) == data

    locs = vs._shard_locations(vid, 13)
    assert vs._shard_loc_cache.get(vid) is not None
    shards, fetched = vs._shard_loc_cache[vid]

    # within the fresh window the cache is served without re-fetching
    again = vs._shard_locations(vid, 13)
    assert vs._shard_loc_cache[vid][1] == fetched
    assert again == locs

    # an unknown shard id within 11s: still cached (no thundering herd)
    vs._shard_locations(vid, 99)
    assert vs._shard_loc_cache[vid][1] == fetched

    # past the missing-shard TTL an unknown shard forces a refresh
    vs._shard_loc_cache[vid] = (shards, fetched - 12.0)
    vs._shard_locations(vid, 99)
    assert vs._shard_loc_cache[vid][1] != fetched - 12.0

    # force=True always refreshes
    t0 = vs._shard_loc_cache[vid][1]
    vs._shard_locations(vid, 13, force=True)
    assert vs._shard_loc_cache[vid][1] >= t0


def test_kill_shard_holder_between_reads(cluster):
    c = cluster
    vid, fids = _setup_ec(c)

    c.client._vid_cache.clear()
    items = list(fids.items())
    for fid, data in items[:3]:
        assert c.client.download(fid) == data

    # find a victim holding few shards (kill must leave >= k survivors)
    info = c.client.ec_lookup(vid)
    by_url: dict = {}
    for sid, urls in info["shards"].items():
        for u in urls:
            by_url.setdefault(u, []).append(int(sid))
    victim_url = min(by_url, key=lambda u: len(by_url[u]))
    assert 14 - len(by_url[victim_url]) >= 10
    idx = next(i for i, vs in enumerate(c.volume_servers)
               if vs.url == victim_url)
    c.stop_volume_server(idx)
    time.sleep(c.pulse * 6)  # dead-node prune + fresh topology

    # reads keep succeeding: missing intervals are fetched from peers or
    # reconstructed from k survivors in parallel
    c.client._vid_cache.clear()
    for fid, data in items:
        assert c.client.download(fid) == data, fid


def test_stale_location_cache_recovers_after_move(cluster):
    c = cluster
    vid, fids = _setup_ec(c)
    c.client._vid_cache.clear()
    fid, data = next(iter(fids.items()))
    assert c.client.download(fid) == data

    # poison every server's location cache with bogus holders; the
    # force-refresh fallback must recover the read
    for vs in c.volume_servers:
        vs._shard_loc_cache[vid] = (
            {str(s): ["127.0.0.1:1"] for s in range(14)}, time.monotonic())
    c.client._vid_cache.clear()
    assert c.client.download(fid) == data
