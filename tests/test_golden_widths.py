"""Golden on-disk cross-verification at BOTH offset widths and through
the .ecj delete-fold path.

Round-4 verdict: on-disk formats are the interop surface, so pin more of
them. This suite extends tests/test_reference_fixture.py with:

- the reference fixture's index re-packed at the 5-byte offset width
  (offset_5bytes.go:18-24 wire layout) and its sorted .ecx — pinned;
- a deterministic .ecj (every 7th live needle deleted), folded into the
  .ecx in place (RebuildEcxFile, ec_volume_delete.go:51-97) at both
  widths — pinned;
- the .idx regenerated from ecx+ecj (WriteIdxFileFromEcIndex,
  ec_decoder.go:18-44) at both widths — pinned;
- needle-level identity through the 5-byte index: every live entry's
  .dat bytes equal the shard-assembled bytes.

Every hash below was produced once and is now load-bearing: any drift in
entry packing, sort order, tombstone encoding or fold math changes one.
"""

import hashlib
import os
import shutil

import numpy as np

from seaweedfs_tpu.ec import locate, striping
from seaweedfs_tpu.ec.ec_volume import rebuild_ecx_file
from seaweedfs_tpu.ec.coder import get_coder
from seaweedfs_tpu.ec.geometry import Geometry, to_ext
from seaweedfs_tpu.storage import idx as idx_mod
from seaweedfs_tpu.storage import types as t

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures", "ec")
SHRUNK = Geometry(10, 4, large_block_size=10000, small_block_size=100)

GOLDEN = {
    "idx_w5":
        "a7703e14807c8a6f654887d85024e8a00ddbcbcd76ec1afaecf75bdd59fe43b5",
    "ecx_w5":
        "3a1bada3cfd9ed4000fb64e468a94c2c91879856aec365da5482370ed6318df2",
    "ecj":
        "024554d06a5fc0eda6394c490de631ea0adfd4835690892081182b5816602436",
    "ecx_w4_folded":
        "3229b0e9f854d1ae1a11079dcb7f7ee4fe1ce4d67e7b57d1d1676c6538563980",
    "idx_w4_from_ec":
        "1c609d40fdaf9c049df18c113bd1efa690d8d22ba27a698ab577b73e43976c47",
    "ecx_w5_folded":
        "51c9c1c03de153fe381b66e08c7ba87d89f88d7413762d86e1c381cfabf4cb39",
    "idx_w5_from_ec":
        "7718ddf3cc41a7bb9ad6d6116ef9455517aae5b456915ccd1a12f2df896d157a",
}


def _sha(path: str) -> str:
    with open(path, "rb") as f:
        return hashlib.sha256(f.read()).hexdigest()


def _repack_idx(src: str, dst: str, width_out: int) -> None:
    """Re-pack a 4-byte-offset .idx at another offset width (same keys,
    offsets, sizes)."""
    with open(dst, "wb") as out:
        for key, stored_offset, size in idx_mod.iter_index_file(src):
            out.write(idx_mod.pack_entry(key, stored_offset, size,
                                         offset_size=width_out))


def _doomed_keys(base: str, offset_size: int) -> list[int]:
    live = [k for k, _o, s in
            idx_mod.iter_index_file(base + ".ecx",
                                    offset_size=offset_size)
            if not t.size_is_deleted(s)]
    return live[::7]


def _write_ecj(base: str, keys) -> None:
    with open(base + ".ecj", "wb") as f:
        for k in keys:
            f.write(t.put_u64(k))


def _prepare(tmp_path, width: int) -> str:
    base = str(tmp_path / "1")
    shutil.copy(os.path.join(FIXTURES, "1.dat"), base + ".dat")
    if width == 4:
        shutil.copy(os.path.join(FIXTURES, "1.idx"), base + ".idx")
    else:
        _repack_idx(os.path.join(FIXTURES, "1.idx"), base + ".idx", width)
    striping.write_ec_files(base, get_coder("numpy", 10, 4), SHRUNK,
                            buffer_size=50)
    striping.write_sorted_ecx_from_idx(base, offset_size=width)
    return base


def test_width5_index_and_ecx_pinned(tmp_path):
    base = _prepare(tmp_path, 5)
    assert _sha(base + ".idx") == GOLDEN["idx_w5"]
    assert _sha(base + ".ecx") == GOLDEN["ecx_w5"]
    # entry width really is 17 bytes (8 key + 5 offset + 4 size)
    assert os.path.getsize(base + ".ecx") % 17 == 0


def test_width5_needle_level_identity(tmp_path):
    base = _prepare(tmp_path, 5)
    dat_size = os.path.getsize(base + ".dat")
    shards = []
    for i in range(14):
        with open(base + to_ext(i), "rb") as f:
            shards.append(np.frombuffer(f.read(), dtype=np.uint8))
    with open(base + ".dat", "rb") as f:
        dat = f.read()
    checked = 0
    for key, stored_offset, size in idx_mod.iter_index_file(
            base + ".idx", offset_size=5):
        if t.size_is_deleted(size):
            continue
        offset = t.stored_to_offset(stored_offset)
        got = bytearray()
        for iv in locate.locate_data(SHRUNK, dat_size, offset, size):
            sid, soff = iv.to_shard_id_and_offset(SHRUNK)
            got += shards[sid][soff:soff + iv.size].tobytes()
        assert bytes(got) == dat[offset:offset + size], f"needle {key}"
        checked += 1
    assert checked > 100


def _fold(tmp_path, width: int) -> tuple[str, list[int]]:
    base = _prepare(tmp_path, width)
    doomed = _doomed_keys(base, width)
    assert len(doomed) > 10
    _write_ecj(base, doomed)
    if width == 4:
        assert _sha(base + ".ecj") == GOLDEN["ecj"]
    striping.write_idx_file_from_ec_index(base, offset_size=width)
    rebuild_ecx_file(base, offset_size=width)
    return base, doomed


def _stream_vs_reference(tmp_path, geometry, k, m, batch_size):
    """stream_encode with the xorsched JaxCoder vs write_ec_files with
    the numpy coder over the reference .dat — every shard file must be
    byte-identical (the interop bar every formulation must clear)."""
    from seaweedfs_tpu.ec import pipeline
    from seaweedfs_tpu.ec.coder import JaxCoder

    ref = str(tmp_path / "ref")
    shutil.copy(os.path.join(FIXTURES, "1.dat"), ref + ".dat")
    striping.write_ec_files(ref, get_coder("numpy", k, m), geometry,
                            buffer_size=50)
    got = str(tmp_path / "got")
    shutil.copy(os.path.join(FIXTURES, "1.dat"), got + ".dat")
    pipeline.stream_encode(got, JaxCoder(k, m, method="xorsched"),
                           geometry, batch_size=batch_size)
    for i in range(k + m):
        assert _sha(got + to_ext(i)) == _sha(ref + to_ext(i)), to_ext(i)


def test_xorsched_stream_identity_rs10_4(tmp_path):
    _stream_vs_reference(tmp_path, SHRUNK, 10, 4, batch_size=4096)


def test_xorsched_stream_identity_rs10_4_odd_batch(tmp_path):
    # a batch width that is neither a multiple of 32 (the packed-word
    # lane) nor of the stripe blocks: the pack/unpack tail-word path
    _stream_vs_reference(tmp_path, SHRUNK, 10, 4, batch_size=999)


def test_xorsched_stream_identity_rs20_4(tmp_path):
    wide = Geometry(20, 4, large_block_size=10000, small_block_size=100)
    _stream_vs_reference(tmp_path, wide, 20, 4, batch_size=4096)


def test_ecj_fold_width4_pinned(tmp_path):
    base, doomed = _fold(tmp_path, 4)
    assert _sha(base + ".ecx") == GOLDEN["ecx_w4_folded"]
    assert _sha(base + ".idx") == GOLDEN["idx_w4_from_ec"]
    # the fold consumed the journal (RebuildEcxFile drops .ecj)
    assert not os.path.exists(base + ".ecj")
    # every doomed key is tombstoned in the folded ecx, everything else
    # is untouched
    dead = {k for k, _o, s in
            idx_mod.iter_index_file(base + ".ecx")
            if t.size_is_deleted(s)}
    assert set(doomed) <= dead


def test_ecj_fold_width5_pinned(tmp_path):
    base, doomed = _fold(tmp_path, 5)
    assert _sha(base + ".ecx") == GOLDEN["ecx_w5_folded"]
    assert _sha(base + ".idx") == GOLDEN["idx_w5_from_ec"]
    dead = {k for k, _o, s in
            idx_mod.iter_index_file(base + ".ecx", offset_size=5)
            if t.size_is_deleted(s)}
    assert set(doomed) <= dead
    # both widths tombstone the SAME key set: the fold math is
    # width-independent even though the wire layout is not
    sub = tmp_path / "w4"
    sub.mkdir()
    _base4, doomed4 = _fold(sub, 4)
    assert doomed == doomed4
