"""Targeted failure injection (VERDICT r2 #9): raft partition without
split-brain, shard-holder death mid degraded-read, filer death
mid-autochunk with orphan cleanup.

The reference exercises these paths operationally (command_volume_fsck.go,
raft_server.go); here they are deterministic tests: the in-process
cluster lets the test intercept the raft transport and the EC interval
reader at exact points.
"""

import hashlib
import json
import os
import signal
import subprocess
import sys
import time
import urllib.error
import urllib.request

import pytest

from cluster_util import TEST_GEOMETRY, Cluster, free_port
from seaweedfs_tpu.shell.ec_commands import EcCommands

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _wait(predicate, timeout=15.0, what=""):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if predicate():
            return
        time.sleep(0.2)
    raise AssertionError(f"timeout waiting for {what}")


# --- (a) network partition between 3 masters: no split-brain ---

def test_partition_no_split_brain():
    c = Cluster(n_volume_servers=1, n_masters=3)
    try:
        masters = c.masters
        _wait(lambda: sum(m.raft.is_leader for m in masters) == 1,
              what="initial leader")
        leader = next(m for m in masters if m.raft.is_leader)
        followers = [m for m in masters if m is not leader]

        # cut the leader off from BOTH followers, both directions, at the
        # raft transport (every vote/append/install rides raft._post)
        def cut(raft_node, peer_rafts):
            orig = raft_node._post
            peer_urls = {p.id for p in peer_rafts}

            async def filtered(peer, path, body,
                               _orig=orig, _urls=peer_urls):
                if peer in _urls:
                    return None  # dropped on the floor: partition
                return await _orig(peer, path, body)

            raft_node._post = filtered
            return orig

        originals = [(leader.raft,
                      cut(leader.raft, [f.raft for f in followers]))]
        for f in followers:
            originals.append((f.raft, cut(f.raft, [leader.raft])))

        # majority side elects a fresh leader at a higher term
        old_term = leader.raft.term
        _wait(lambda: sum(f.raft.is_leader for f in followers) == 1,
              what="new leader on the majority side")
        new_leader = next(f for f in followers if f.raft.is_leader)
        assert new_leader.raft.term > old_term

        # the partition isolates the old leader from the volume server
        # too (full network split): its heartbeats land on the majority
        vs = c.volume_servers[0]
        vs_masters_before = list(vs.masters)
        vs.masters = [new_leader.url]
        vs.master_url = new_leader.url

        # the stale leader may still CLAIM leadership, but it cannot
        # commit: an assign through it must not mint a fid (the
        # leader-readiness barrier needs quorum) — so at no point can two
        # masters both serve writes
        if leader.raft.is_leader:
            try:
                with urllib.request.urlopen(
                        f"http://{leader.url}/dir/assign", timeout=8) as r:
                    body = json.load(r)
                assert "fid" not in body, \
                    "stale leader minted a fid without quorum: split-brain"
            except (urllib.error.HTTPError, urllib.error.URLError,
                    TimeoutError, OSError):
                pass  # refusing/timing out is equally safe

        # the real leader keeps assigning (volume servers need a pulse or
        # two to re-home their heartbeats onto it first)
        def new_leader_assigns():
            try:
                with urllib.request.urlopen(
                        f"http://{new_leader.url}/dir/assign",
                        timeout=10) as r:
                    return "fid" in json.load(r)
            except (urllib.error.HTTPError, urllib.error.URLError,
                    OSError):
                return False

        _wait(new_leader_assigns, timeout=20,
              what="assign through the new leader")

        # heal: the stale leader sees the higher term and steps down
        for raft_node, orig in originals:
            raft_node._post = orig
        vs.masters = vs_masters_before
        _wait(lambda: sum(m.raft.is_leader for m in masters) == 1
              and leader.raft.term >= new_leader.raft.term,
              what="partition heal -> single leader, converged terms")
        assert sum(m.raft.is_leader for m in masters) == 1
    finally:
        c.shutdown()


# --- (b) shard holder dies mid degraded-read ---

def test_shard_holder_killed_mid_degraded_read():
    c = Cluster(n_volume_servers=4)
    try:
        import random
        rng = random.Random(5)
        data = bytes(rng.getrandbits(8) for _ in range(60_000))
        fid = c.client.upload(data, collection="chaos")
        c.wait_heartbeats()
        vid = int(fid.split(",")[0])
        EcCommands(c.client, TEST_GEOMETRY).encode(vid, "chaos", apply=True)
        c.wait_heartbeats()

        # the reading server holds SOME shards; remote intervals come from
        # peers. Kill one remote holder after two intervals have already
        # been assembled — deterministically mid-read.
        reader_vs = next(vs for vs in c.volume_servers
                         if vs.store.find_ec_volume(vid) is not None)
        ev = reader_vs.store.find_ec_volume(vid)
        victim = next(vs for vs in c.volume_servers
                      if vs is not reader_vs
                      and vs.store.find_ec_volume(vid) is not None)

        calls = {"n": 0}
        orig = ev._read_interval

        def chaotic(iv, shard_reader, _orig=orig):
            calls["n"] += 1
            if calls["n"] == 2:
                _kill_volume_server(c, victim)
            return _orig(iv, shard_reader)

        ev._read_interval = chaotic
        got = urllib.request.urlopen(
            f"http://{reader_vs.url}/{fid}", timeout=60).read()
        assert hashlib.sha256(got).hexdigest() == \
            hashlib.sha256(data).hexdigest()
        assert calls["n"] >= 2, "read finished before the injection"
    finally:
        c.shutdown()


def _kill_volume_server(c, vs) -> None:
    """Dirty in-process death: drop its EC state and stop its HTTP
    listener so in-flight fetches to it fail."""
    port = vs.url.rsplit(":", 1)[1]
    for loc in vs.store.locations:
        for v_ in list(loc.ec_volumes.values()):
            v_.close()
        loc.ec_volumes.clear()

    async def halt():
        for runner in list(c.runners):
            addrs = [str(a) for a in getattr(runner, "addresses", [])]
            if any(a.endswith(f", {port})") or f":{port}" in a
                   for a in addrs):
                await runner.cleanup()
                return

    c.call(halt())


# --- (c) filer dies mid-autochunk; fsck finds no surviving orphans ---

def _spawn(args, cwd, log_name):
    env = dict(os.environ, SEAWEEDFS_FORCE_CPU="1")
    env["PYTHONPATH"] = ":".join(
        p for p in (env.get("PYTHONPATH", ""), _REPO_ROOT) if p)
    log = open(os.path.join(cwd, f"{log_name}.log"), "ab")
    return subprocess.Popen(
        [sys.executable, "-m", "seaweedfs_tpu.cli"] + args,
        cwd=cwd, env=env, stdout=log, stderr=log)


def _wait_http(url, timeout=25):
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            with urllib.request.urlopen(url, timeout=2) as r:
                return json.load(r)
        except Exception:
            time.sleep(0.2)
    raise TimeoutError(url)


def test_filer_killed_mid_autochunk_orphans_cleaned(tmp_path):
    from seaweedfs_tpu.client import Client
    from seaweedfs_tpu.shell import commands as shell_commands
    from seaweedfs_tpu.shell.commands import CommandEnv, run_command
    shell_commands._register_all()

    mport, vport, fport = free_port(), free_port(), free_port()
    master = f"127.0.0.1:{mport}"
    filer = f"127.0.0.1:{fport}"
    d = str(tmp_path)
    os.makedirs(os.path.join(d, "vol"), exist_ok=True)
    procs = []
    try:
        procs.append(_spawn(["master", "-port", str(mport),
                             "-mdir", d], d, "master"))
        procs.append(_spawn(["volume", "-port", str(vport), "-dir",
                             os.path.join(d, "vol"), "-mserver", master,
                             "-pulse", "1"], d, "volume"))
        _wait_http(f"http://{master}/cluster/status")
        filer_proc = _spawn(["filer", "-port", str(fport), "-mserver",
                             master, "-store_path",
                             os.path.join(d, "filer.db"),
                             "-chunk_size_mb", "1"], d, "filer")
        procs.append(filer_proc)
        _wait_http(f"http://{filer}/__meta__/info")

        # stream a 6MB PUT in drips; SIGKILL the filer once several 1MB
        # chunks have already landed on the volume server
        import http.client
        conn = http.client.HTTPConnection("127.0.0.1", fport, timeout=30)
        chunk = b"z" * 65536
        total = 6 * 1024 * 1024
        conn.putrequest("PUT", "/partial/big.bin")
        conn.putheader("Content-Length", str(total))
        conn.endheaders()
        sent = 0
        try:
            while sent < total:
                conn.send(chunk)
                sent += len(chunk)
                if sent == 3 * 1024 * 1024:
                    time.sleep(0.5)  # let flushed chunks reach volumes
                    filer_proc.send_signal(signal.SIGKILL)
                    filer_proc.wait(timeout=10)
        except OSError:
            pass
        finally:
            conn.close()

        # restart the filer over the same store; the torn upload has no
        # entry, so its already-written chunks are orphans
        procs.append(_spawn(["filer", "-port", str(fport), "-mserver",
                             master, "-store_path",
                             os.path.join(d, "filer.db"),
                             "-chunk_size_mb", "1"], d, "filer2"))
        _wait_http(f"http://{filer}/__meta__/info")
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(f"http://{filer}/partial/big.bin",
                                   timeout=5)

        env = CommandEnv(Client(master), filer=filer)
        out1 = run_command(env, "volume.fsck")
        assert out1["orphan_count"] > 0, \
            "expected orphan chunks after the mid-upload kill"
        out2 = run_command(env, "volume.fsck -purgeOrphans")
        assert out2["purged"] == out2["orphan_count"]
        out3 = run_command(env, "volume.fsck")
        assert out3["orphan_count"] == 0, "orphans survived the purge"
    finally:
        for p in procs:
            try:
                p.kill()
            except OSError:
                pass
