"""The always-on sampling profiler (observe/profiler.py): lifecycle,
collapsed/flame output, request-class tagging, the distinct-stack cap,
and the overhead bound that justifies running it in every server.
"""

import threading
import time

from seaweedfs_tpu import observe
from seaweedfs_tpu.observe import profiler


def _busy(stop: threading.Event) -> None:
    x = 0
    while not stop.is_set():
        for i in range(2000):
            x += i * i


def test_start_stop_and_sampling():
    p = profiler.SamplingProfiler(hz=200)
    stop = threading.Event()
    th = threading.Thread(target=_busy, args=(stop,), daemon=True)
    th.start()
    try:
        p.start()
        assert p.running
        p.start()  # idempotent
        time.sleep(0.4)
    finally:
        p.stop()
        stop.set()
        th.join()
    assert not p.running
    assert p.samples > 10
    stats = p.stats()
    assert stats["distinct_stacks"] > 0
    assert stats["hz"] == 200

    # collapsed: "class;frame;frame... count" lines, counts numeric
    text = p.collapsed()
    assert text
    for line in text.strip().splitlines():
        stack, _, count = line.rpartition(" ")
        assert stack and count.isdigit()
    # the busy thread's frames were captured somewhere in the fold
    assert "_busy" in text

    # flame JSON nests name/value/children and conserves counts
    flame = p.flame()
    assert flame["name"] == "all"
    assert flame["value"] == sum(
        int(line.rpartition(" ")[2])
        for line in text.strip().splitlines())

    p.reset()
    assert p.stats()["samples"] == 0
    assert p.collapsed() == ""


def test_request_tagging_attributes_samples():
    profiler.shutdown()
    try:
        # request_tag is a no-op without the process profiler
        with profiler.request_tag("fg", "t-none"):
            pass

        p = profiler.ensure_started()
        assert p is not None
        assert profiler.ensure_started() is p  # singleton

        stop = threading.Event()

        def tagged():
            with profiler.request_tag("fg", "trace-tag-1"):
                _busy(stop)

        th = threading.Thread(target=tagged, daemon=True)
        th.start()
        try:
            deadline = time.time() + 5
            while time.time() < deadline:
                if p.stats()["samples_by_class"].get("fg", 0) >= 3:
                    break
                time.sleep(0.05)
        finally:
            stop.set()
            th.join()
        by_cls = p.stats()["samples_by_class"]
        assert by_cls.get("fg", 0) >= 3, by_cls
        # the class filter serves only that class's stacks, and the fg
        # stacks carry the tagging request's trace id
        fg_only = p.collapsed(cls_filter="fg")
        assert fg_only and all(line.startswith("fg;")
                               for line in fg_only.strip().splitlines())
        assert any(trace == "trace-tag-1"
                   for _, _, _, trace in p._snapshot_stacks())
    finally:
        profiler.shutdown()


def test_distinct_stack_cap_counts_drops():
    p = profiler.SamplingProfiler(hz=100, max_stacks=2)
    with p._lock:
        p._stacks[("fg", ("a",))] = [1, ""]
        p._stacks[("fg", ("b",))] = [1, ""]
    stop = threading.Event()
    th = threading.Thread(target=_busy, args=(stop,), daemon=True)
    th.start()
    try:
        p.start()
        time.sleep(0.3)
    finally:
        p.stop()
        stop.set()
        th.join()
    # new stacks beyond the cap were dropped and counted, not stored
    assert len(p._stacks) == 2
    assert p.dropped > 0
    assert p.stats()["dropped_stacks"] == p.dropped


def test_sampler_overhead_bound():
    """At the default 19Hz the sampler must not meaningfully slow a
    CPU-bound workload — the property that makes always-on viable.  The
    in-test bound is deliberately loose (2x the ISSUE's 3% production
    gate) to stay robust on noisy CI hosts; bench.py --phase observe
    measures the real number."""

    def work() -> float:
        t0 = time.perf_counter()
        x = 0
        for i in range(2_000_000):
            x += i * i
        return time.perf_counter() - t0

    work()  # warm up
    base = min(work() for _ in range(3))
    p = profiler.SamplingProfiler(hz=19)
    p.start()
    try:
        sampled = min(work() for _ in range(3))
    finally:
        p.stop()
    assert sampled <= base * 1.5, (base, sampled)


def test_request_tag_survives_interleaving():
    """Exit must clear the tag only when still its own: a newer request
    re-tagging the thread keeps its tag when an older one unwinds."""
    profiler.shutdown()
    p = profiler.ensure_started()
    assert p is not None
    try:
        tid = threading.get_ident()
        outer = profiler.request_tag("fg", "outer-trace")
        inner = profiler.request_tag("bg", "inner-trace")
        outer.__enter__()
        inner.__enter__()
        # outer unwinds first (asyncio interleaving): inner's tag stays
        outer.__exit__(None, None, None)
        assert profiler._request_tags.get(tid) == ("bg", "inner-trace")
        inner.__exit__(None, None, None)
        assert tid not in profiler._request_tags
    finally:
        profiler.shutdown()


def test_span_ring_snapshot_under_concurrent_records():
    """Regression for the snapshot-under-lock read pattern: readers
    iterating the span ring while writer threads append must never see a
    'deque mutated during iteration' error."""
    observe.reset()
    stop = threading.Event()
    errors = []

    def writer():
        ctx = observe.TraceCtx("hammer", "", "unit", "")
        while not stop.is_set():
            observe.record_span("w", ctx, 0, 1)

    def reader():
        try:
            while not stop.is_set():
                observe.spans()
                observe.stage_totals()
        except Exception as e:  # pragma: no cover - the regression
            errors.append(e)

    threads = ([threading.Thread(target=writer, daemon=True)
                for _ in range(3)]
               + [threading.Thread(target=reader, daemon=True)
                  for _ in range(2)])
    for t in threads:
        t.start()
    time.sleep(0.5)
    stop.set()
    for t in threads:
        t.join()
    assert not errors
    observe.reset()
