"""Tests for the config / logging / security substrate.

Mirrors the reference's coverage of weed/util/config.go, weed/glog,
weed/security/{jwt,guard}.go.
"""

import os
import time

import pytest

from seaweedfs_tpu.security import guard as guard_mod
from seaweedfs_tpu.security import jwt as jwt_mod
from seaweedfs_tpu.utils import config as config_mod
from seaweedfs_tpu.utils import glog


# --- config ---

def test_toml_load_and_dotted_access(tmp_path):
    (tmp_path / "security.toml").write_text(
        '[jwt.signing]\nkey = "sekrit"\nexpires_after_seconds = 11\n'
        '[guard]\nwhite_list = "10.0.0.1,192.168.0.0/16"\n')
    cfg = config_mod.load_configuration(
        "security", search_paths=[str(tmp_path)])
    assert cfg.get_string("jwt.signing.key") == "sekrit"
    assert cfg.get_int("jwt.signing.expires_after_seconds") == 11
    assert cfg.get_string("guard.white_list").startswith("10.0.0.1")
    assert cfg.get_string("jwt.signing.read.key", "") == ""


def test_env_override(tmp_path, monkeypatch):
    (tmp_path / "security.toml").write_text('[jwt.signing]\nkey = "a"\n')
    monkeypatch.setenv("WEED_JWT_SIGNING_KEY", "from-env")
    monkeypatch.setenv("WEED_JWT_SIGNING_EXPIRES_AFTER_SECONDS", "99")
    cfg = config_mod.load_configuration(
        "security", search_paths=[str(tmp_path)])
    assert cfg.get_string("jwt.signing.key") == "from-env"
    assert cfg.get_int("jwt.signing.expires_after_seconds", 10) == 99


def test_missing_config_is_empty_not_error(tmp_path):
    cfg = config_mod.load_configuration("nope", search_paths=[str(tmp_path)])
    assert cfg.get("anything", 42) == 42
    with pytest.raises(FileNotFoundError):
        config_mod.load_configuration("nope", required=True,
                                      search_paths=[str(tmp_path)])


# --- glog ---

def test_glog_verbosity_and_vmodule():
    glog.setup(1, "test_substrate=3")
    assert glog.v(1)
    assert glog.v(3)      # vmodule override for this file
    assert not glog.v(4)
    glog.setup(0)
    assert glog.v(0)
    assert not glog.v(1)


# --- jwt ---

def test_jwt_roundtrip_and_fid_binding():
    tok = jwt_mod.GenJwt("key1", 60, "3,01637037d6")
    claims = jwt_mod.DecodeJwt("key1", tok)
    assert claims["fid"] == "3,01637037d6"
    jwt_mod.VerifyFid("key1", tok, "3,01637037d6")
    with pytest.raises(jwt_mod.JwtError):
        jwt_mod.VerifyFid("key1", tok, "4,anotherfid")
    with pytest.raises(jwt_mod.JwtError):
        jwt_mod.DecodeJwt("wrong-key", tok)


def test_jwt_expiry():
    tok = jwt_mod.GenJwt("k", -1, "1,ab")  # exp in the past
    # exp <= 0 means no expiry claim is even set when expires_seconds==0
    tok_expired = jwt_mod.GenJwt("k", 1, "1,ab")
    claims = jwt_mod.DecodeJwt("k", tok_expired)
    assert claims["exp"] >= int(time.time())
    # forge an expired token
    import base64
    import hashlib
    import hmac
    import json as _json
    payload = base64.urlsafe_b64encode(_json.dumps(
        {"fid": "1,ab", "exp": int(time.time()) - 5}).encode()) \
        .rstrip(b"=").decode()
    msg = f"{jwt_mod._HEADER}.{payload}"
    sig = base64.urlsafe_b64encode(
        hmac.new(b"k", msg.encode(), hashlib.sha256).digest()) \
        .rstrip(b"=").decode()
    with pytest.raises(jwt_mod.JwtError, match="expired"):
        jwt_mod.DecodeJwt("k", f"{msg}.{sig}")


def test_jwt_empty_key_disables():
    assert jwt_mod.GenJwt("", 60, "1,ab") == ""


# --- guard ---

def test_guard_whitelist():
    g = guard_mod.Guard(whitelist=["127.0.0.1", "10.1.0.0/16"])
    assert g.check_whitelist("127.0.0.1")
    assert g.check_whitelist("10.1.200.7")
    assert not g.check_whitelist("10.2.0.1")
    assert not g.check_whitelist("8.8.8.8")
    open_g = guard_mod.Guard()
    assert open_g.check_whitelist("8.8.8.8")


def test_guard_write_verify_cycle():
    g = guard_mod.Guard(signing_key="shh")
    tok = g.sign_write("7,aa11")
    assert g.verify_write(tok, "7,aa11") is None
    assert g.verify_write(tok, "8,bb22") is not None
    assert g.verify_write("", "7,aa11") == "missing jwt"
    # open guard: no key -> everything passes
    assert guard_mod.Guard().verify_write("", "7,aa11") is None


def test_token_from_request():
    assert guard_mod.token_from_request(
        {"Authorization": "BEARER abc.def.ghi"}, {}) == "abc.def.ghi"
    assert guard_mod.token_from_request({}, {"jwt": "qq"}) == "qq"
    assert guard_mod.token_from_request({}, {}) == ""


# --- end-to-end: jwt-secured cluster ---

def test_jwt_enforced_end_to_end():
    from cluster_util import Cluster

    from seaweedfs_tpu.client import ClientError

    c = Cluster(n_volume_servers=1)
    try:
        g = guard_mod.Guard(signing_key="topsecret")
        c.master.guard = g
        for vs in c.volume_servers:
            vs.guard = g
        a = c.client.assign()
        assert a.get("auth"), "master must sign a write token"
        c.client.upload_blob(a["url"], a["fid"], b"hello", auth=a["auth"])
        with pytest.raises(ClientError):
            c.client.upload_blob(a["url"], a["fid"], b"hello")  # no token
        with pytest.raises(ClientError):
            c.client.upload_blob(a["url"], a["fid"], b"hello",
                                 auth=jwt_mod.GenJwt("wrong", 10, a["fid"]))
        # reads stay open when no read key is configured
        assert c.client.download(a["fid"]) == b"hello"
    finally:
        c.shutdown()


# --- scaffold ---

def test_scaffold_templates_parse(tmp_path):
    try:
        import tomllib
    except ModuleNotFoundError:  # Python < 3.11
        import tomli as tomllib

    from seaweedfs_tpu.utils.scaffold import TEMPLATES
    assert set(TEMPLATES) == {"security", "filer", "master",
                              "notification", "replication"}
    for name, text in TEMPLATES.items():
        tomllib.loads(text)  # every template is valid TOML

def test_master_guard_whitelist_enforced():
    """A non-whitelisted IP must be rejected on every master route except
    /healthz (guard.WhiteList around master handlers,
    weed/server/master_server.go:115-126)."""
    import json
    import urllib.error
    import urllib.request

    from cluster_util import Cluster

    c = Cluster(n_volume_servers=1)
    try:
        # replace guard with one that excludes localhost
        c.master.guard = guard_mod.Guard(whitelist=["10.9.9.9"])
        base = f"http://{c.master_url}"
        with urllib.request.urlopen(f"{base}/healthz", timeout=5) as r:
            assert json.load(r)["ok"]
        for path in ("/dir/assign", "/dir/lookup?volumeId=1",
                     "/dir/status", "/cluster/status"):
            try:
                urllib.request.urlopen(base + path, timeout=5)
                raise AssertionError(f"{path} not guarded")
            except urllib.error.HTTPError as e:
                assert e.code == 403, path
        # restoring an open guard restores access
        c.master.guard = guard_mod.Guard()
        with urllib.request.urlopen(f"{base}/dir/status", timeout=5) as r:
            json.load(r)
    finally:
        c.shutdown()


def test_filer_deletion_worker_signs_jwt():
    """With jwt.signing.key configured, the filer's chunk-deletion worker
    must sign write jwts so volume servers accept the DELETE — otherwise
    freed chunks leak (reference signs deletion jwts with the shared key)."""
    import time as time_mod
    import urllib.request

    from cluster_util import Cluster

    c = Cluster(n_volume_servers=1)
    try:
        g = guard_mod.Guard(signing_key="delete-secret")
        c.master.guard = g
        for vs in c.volume_servers:
            vs.guard = g
        filer = c.add_filer()
        filer.guard = g
        # upload through the filer, then delete the file
        req = urllib.request.Request(
            f"http://{filer.url}/del-me.bin", data=b"x" * 1000, method="PUT")
        urllib.request.urlopen(req, timeout=10).close()
        fid = filer.filer.find_entry("/del-me.bin").chunks[0].fid
        req = urllib.request.Request(
            f"http://{filer.url}/del-me.bin", method="DELETE")
        urllib.request.urlopen(req, timeout=10).close()
        # the chunk must actually be gone from the volume server
        deadline = time_mod.time() + 5
        gone = False
        while time_mod.time() < deadline:
            try:
                urllib.request.urlopen(
                    f"http://{c.volume_servers[0].url}/{fid}", timeout=5)
            except urllib.error.HTTPError as e:
                if e.code == 404:
                    gone = True
                    break
            time_mod.sleep(0.1)
        assert gone, "chunk not reclaimed — deletion jwt missing?"
    finally:
        c.shutdown()


def test_replicator_offset_persistence(tmp_path):
    """Replicator.run persists the last applied tsns and resumes from it
    (filer_sync.go setOffset/getOffset)."""
    from seaweedfs_tpu.replication.replicator import Replicator

    r = Replicator("127.0.0.1:1", None,
                   offset_path=str(tmp_path / "off.json"))
    assert r.load_offset() == 0
    r.save_offset(12345)
    assert r.load_offset() == 12345
    r2 = Replicator("127.0.0.1:1", None,
                    offset_path=str(tmp_path / "off.json"))
    assert r2.load_offset() == 12345


# --- round-3 advisor-finding regressions ---

def test_needle_raw_denies_with_only_read_key():
    """admin_needle_raw serves raw needle content, so with ONLY a read
    key configured it must still demand a read JWT. The old check
    required BOTH regimes to fail; verify_write passes vacuously when no
    write key is set, so unauthenticated raw reads slipped through."""
    import urllib.error
    import urllib.request

    from cluster_util import Cluster

    c = Cluster(n_volume_servers=1)
    try:
        fid = c.client.upload(b"secret bytes " * 10)
        c.wait_heartbeats()
        g = guard_mod.Guard(read_signing_key="read-only-key")
        for vs in c.volume_servers:
            vs.guard = g
        vs = next(v for v in c.volume_servers
                  if v.store.find_volume(int(fid.split(",")[0])))
        base = f"http://{vs.url}/admin/needle_raw?fid={fid}"
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(base, timeout=5)
        assert e.value.code == 401
        # a valid read token unlocks it
        tok = g.sign_read(fid)
        with urllib.request.urlopen(f"{base}&jwt={tok}", timeout=5) as r:
            assert r.status == 200 and b"secret bytes" in r.read()
        # ... and a write token under a write key does too
        g2 = guard_mod.Guard(signing_key="write-key")
        for v in c.volume_servers:
            v.guard = g2
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(base, timeout=5)
        assert e.value.code == 401
        tok = g2.sign_write(fid)
        with urllib.request.urlopen(f"{base}&jwt={tok}", timeout=5) as r:
            assert r.status == 200
    finally:
        c.shutdown()


def test_raft_peer_ips_resolve_hostnames():
    """Peers configured by hostname (k8s service names) must still match
    request.remote, which is always an IP — otherwise every raft RPC is
    403'd and no leader can ever be elected."""
    from seaweedfs_tpu.server.master import MasterServer

    ips = MasterServer._resolve_peer_ips(
        ["localhost:9334", "10.0.0.7:9333"])
    assert "127.0.0.1" in ips          # resolved from the hostname
    assert "localhost" in ips          # literal kept too
    assert "10.0.0.7" in ips
    # unresolvable names keep the literal and don't raise
    ips = MasterServer._resolve_peer_ips(["no-such-host.invalid:9333"])
    assert "no-such-host.invalid" in ips


def test_write_batcher_retires_idle_and_dead_volume_workers():
    """WriteBatcher workers for unmounted/bogus volumes exit instead of
    idling forever (advisor round-2, low)."""
    import asyncio as aio

    from seaweedfs_tpu.server.volume_server import WriteBatcher

    class _NoStore:
        def find_volume(self, vid):
            return None

    async def run():
        b = WriteBatcher(_NoStore())
        b.IDLE_SECONDS = 0.05
        with pytest.raises(KeyError):
            await b.write(42, type("N", (), {"data": b"x"})())
        # the dead-volume worker retires promptly
        for _ in range(100):
            if not b._workers and not b._queues:
                break
            await aio.sleep(0.01)
        assert not b._workers and not b._queues
        b.stop()

    aio.run(run())


def test_raft_save_state_is_durable(tmp_path):
    """_save_state fsyncs file + directory so a granted vote survives
    power loss (election safety)."""
    import os as os_mod
    from unittest import mock

    from seaweedfs_tpu.cluster.raft import RaftNode

    n = RaftNode("me", [], apply_fn=lambda cmd: None,
                 state_dir=str(tmp_path))
    n.term = 7
    n.voted_for = "peer-a"
    synced = []
    real_fsync = os_mod.fsync
    with mock.patch("os.fsync", side_effect=lambda fd: (synced.append(fd),
                                                        real_fsync(fd))):
        n._save_state()
    # at least two fsyncs: the tmp file and the containing directory
    assert len(synced) >= 2
