"""Fault-injection plane unit + e2e: spec parsing, WEED_FAULTS env,
deterministic probability/corruption, budgets, and the /admin/faults
endpoint flipping real server behavior declaratively."""

import json
import time
import urllib.error
import urllib.request

import pytest

from cluster_util import Cluster
from seaweedfs_tpu import faults


@pytest.fixture(autouse=True)
def _clean_registry():
    faults.clear()
    yield
    faults.clear()


def test_spec_parsing_round_trip():
    f = faults._parse_spec("volume.read:error:p=0.5:count=3:seed=7")
    assert (f.point, f.action, f.p, f.count, f.seed) == \
        ("volume.read", "error", 0.5, 3, 7)
    f = faults._parse_spec("ec.shard_read:delay:ms=200")
    assert f.action == "delay" and f.ms == 200.0 and f.count is None
    with pytest.raises(ValueError):
        faults._parse_spec("justapoint")
    with pytest.raises(ValueError):
        faults._parse_spec("p:unknownaction")
    with pytest.raises(ValueError):
        faults._parse_spec("p:error:bogus=1")


def test_env_loading(monkeypatch):
    monkeypatch.setenv("WEED_FAULTS",
                       "a.b:error:count=1, c.d:delay:ms=5")
    monkeypatch.setattr(faults, "_env_loaded", False)
    monkeypatch.setattr(faults, "_faults", [])
    assert {f["point"] for f in faults.active()} == {"a.b", "c.d"}
    with pytest.raises(faults.FaultError):
        faults.fire("a.b")
    assert faults.fire("a.b") is False  # budget spent


def test_count_budget_and_drop():
    faults.set_fault("x", "drop", count=2)
    assert faults.fire("x") is True
    assert faults.fire("x") is True
    assert faults.fire("x") is False
    assert faults.active()[0]["fired"] == 2


def test_probability_deterministic_with_seed():
    def run():
        faults.clear()
        faults.set_fault("p", "drop", p=0.5, seed=42)
        return [faults.fire("p") for _ in range(50)]

    a, b = run(), run()
    assert a == b, "same seed must replay the same decision stream"
    assert 5 < sum(a) < 45, "p=0.5 should fire sometimes, not always"


def test_delay_fault_sleeps():
    faults.set_fault("d", "delay", ms=50, count=1)
    t0 = time.perf_counter()
    assert faults.fire("d") is False
    assert time.perf_counter() - t0 >= 0.045


def test_corrupt_flips_exactly_one_byte_deterministically():
    data = bytes(range(256))
    faults.set_fault("c", "corrupt", seed=3, count=2)
    out1 = faults.corrupt("c", data)
    diff = [i for i in range(256) if out1[i] != data[i]]
    assert len(diff) == 1 and out1[diff[0]] == data[diff[0]] ^ 0xFF
    # a corrupt fault is never consumed by flow-control fire()
    faults.clear()
    faults.set_fault("c", "corrupt", count=1)
    assert faults.fire("c") is False
    assert faults.corrupt("c", b"abc") != b"abc"


def test_prefix_wildcard_points():
    faults.set_fault("rpc.*", "drop", count=2)
    assert faults.fire("rpc.Assign") is True
    assert faults.fire("volume.read") is False
    assert faults.fire("rpc.Lookup") is True


def test_admin_endpoint_flips_server_behavior():
    """POST /admin/faults on one volume server: its reads fail exactly
    `count` times, then recover — no monkeypatching anywhere."""
    c = Cluster(n_volume_servers=1)
    try:
        fid = c.client.upload(b"fault-plane-payload")
        url = c.client.lookup(int(fid.split(",")[0]))[0]

        req = urllib.request.Request(
            f"http://{url}/admin/faults",
            data=json.dumps(
                {"set": [{"point": "volume.read", "action": "error",
                          "count": 2}]}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=10) as r:
            listed = json.load(r)["faults"]
        assert any(f["point"] == "volume.read" for f in listed)

        for _ in range(2):
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(f"http://{url}/{fid}", timeout=10)
            assert ei.value.code == 500
        with urllib.request.urlopen(f"http://{url}/{fid}",
                                    timeout=10) as r:
            assert r.read() == b"fault-plane-payload"

        # GET lists the firing count; clear empties the registry
        with urllib.request.urlopen(f"http://{url}/admin/faults",
                                    timeout=10) as r:
            assert json.load(r)["faults"][0]["fired"] == 2
        req = urllib.request.Request(
            f"http://{url}/admin/faults",
            data=json.dumps({"clear": "*"}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=10) as r:
            assert json.load(r)["faults"] == []
    finally:
        c.shutdown()
