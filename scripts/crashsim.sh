#!/usr/bin/env bash
# crashsim CI gate: power-loss simulation sweep over every persistence
# path (volume append, needle-map flush, EC encode/.ecm, raft/metalog
# snapshots, replication offsets, filer KV). Fails on any durability-
# contract violation: acked-write loss, silent corruption load, or a
# recovery that does not converge.
#
#   scripts/crashsim.sh                      # the CI budget (>=200 points)
#   scripts/crashsim.sh --seeds 5 --points 50    # deeper sweep
#   scripts/crashsim.sh --workloads volume_append --json
#
# Runs beside scripts/lint.sh; JAX is not needed (CPU-only numpy paths).
set -euo pipefail
cd "$(dirname "$0")/.."
exec env JAX_PLATFORMS=cpu python -m seaweedfs_tpu.crashsim \
    --seeds 2 --points 20 "$@"
