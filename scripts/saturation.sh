#!/usr/bin/env bash
# Shard-fleet saturation smoke: master + WEED_SERVE_SHARDS=2 volume
# server (SO_REUSEPORT fork fleet), ~5s of concurrent PUT/GET traffic.
# Fails on any 5xx/transport error, any non-byte-identical read-back,
# or /healthz reporting fewer live shards than configured.
#
#   scripts/saturation.sh                          # 2 shards, 5s
#   WEED_SERVE_SHARDS=4 SAT_SECONDS=10 scripts/saturation.sh
#   WEED_VOLUME_GROUP_COMMIT_US=500 scripts/saturation.sh   # + group commit
set -euo pipefail
cd "$(dirname "$0")/.."
exec env JAX_PLATFORMS=cpu python scripts/saturation.py "$@"
