"""2-shard saturation smoke: boots a master + a WEED_SERVE_SHARDS=2
volume server (the SO_REUSEPORT fleet forked by the CLI), then drives
concurrent PUT/GET traffic for a few seconds.

Pass criteria (any failure exits non-zero):
  * zero 5xx / transport errors across the storm;
  * every uploaded blob reads back byte-identical afterwards (covers
    the sendfile path, cross-shard proxying, and group commit when
    WEED_VOLUME_GROUP_COMMIT_US is set in the environment);
  * /healthz on the shared port reports both shards alive.

Invoked by scripts/saturation.sh; knobs: SAT_SECONDS (default 5),
SAT_THREADS (default 8), WEED_SERVE_SHARDS (default 2).
"""

import hashlib
import json
import os
import shutil
import socket
import subprocess
import sys
import tempfile
import threading
import time
import urllib.request


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def wait_http(url: str, timeout: float = 30.0) -> None:
    deadline = time.time() + timeout
    last = None
    while time.time() < deadline:
        try:
            urllib.request.urlopen(url, timeout=2).read()
            return
        except Exception as e:  # noqa: BLE001 - startup polling
            last = e
            time.sleep(0.2)
    raise SystemExit(f"timeout waiting for {url}: {last}")


def main() -> int:
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from seaweedfs_tpu.client import Client

    shards = int(os.environ.get("WEED_SERVE_SHARDS", "2") or 2)
    seconds = float(os.environ.get("SAT_SECONDS", "5") or 5)
    threads_n = int(os.environ.get("SAT_THREADS", "8") or 8)
    tmp = tempfile.mkdtemp(prefix="swfs-sat-")
    os.makedirs(os.path.join(tmp, "m"))
    os.makedirs(os.path.join(tmp, "v"))
    mport, vport = free_port(), free_port()
    env = dict(os.environ, JAX_PLATFORMS="cpu", SEAWEEDFS_FORCE_CPU="1",
               WEED_SERVE_SHARDS=str(shards))
    procs = []
    try:
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "seaweedfs_tpu.cli", "master",
             "-port", str(mport), "-mdir", os.path.join(tmp, "m"),
             "-grpc_port", "0", "-pulse", "1"], env=env))
        wait_http(f"http://127.0.0.1:{mport}/healthz")
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "seaweedfs_tpu.cli", "volume",
             "-port", str(vport), "-dir", os.path.join(tmp, "v"),
             "-mserver", f"127.0.0.1:{mport}", "-grpc_port", "0",
             "-pulse", "1"], env=env))
        wait_http(f"http://127.0.0.1:{vport}/healthz")
        # let the shards publish their first heartbeats/blobs
        time.sleep(2.0)

        client = Client(f"127.0.0.1:{mport}")
        # warmup: the first assign races the master's initial volume
        # growth; retry until a volume is writable so the storm only
        # measures steady-state behavior
        warm_deadline = time.time() + 30.0
        while True:
            try:
                client.upload(b"warmup", filename="warmup")
                break
            except Exception as e:  # noqa: BLE001 - startup polling
                if time.time() > warm_deadline:
                    raise SystemExit(f"warmup upload never succeeded: {e}")
                time.sleep(0.5)
        stop = time.time() + seconds
        lock = threading.Lock()
        written: dict[str, str] = {}      # fid -> sha256
        errors: list[str] = []
        counts = {"put": 0, "get": 0}

        def worker(idx: int) -> None:
            rng_i = 0
            while time.time() < stop:
                rng_i += 1
                data = hashlib.sha256(
                    f"{idx}:{rng_i}".encode()).digest() * (idx % 7 + 1)
                try:
                    fid = client.upload(data, filename=f"s{idx}-{rng_i}")
                    with lock:
                        written[fid] = hashlib.sha256(data).hexdigest()
                        counts["put"] += 1
                except Exception as e:  # noqa: BLE001 - tallied below
                    with lock:
                        errors.append(f"put: {e}")
                    continue
                try:
                    back = client.download(fid)
                    with lock:
                        counts["get"] += 1
                    if hashlib.sha256(back).hexdigest() != \
                            hashlib.sha256(data).hexdigest():
                        with lock:
                            errors.append(f"get {fid}: bytes differ")
                except Exception as e:  # noqa: BLE001 - tallied below
                    with lock:
                        errors.append(f"get {fid}: {e}")

        ts = [threading.Thread(target=worker, args=(i,))
              for i in range(threads_n)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()

        # full read-back pass: every acked write must come back
        # byte-identical after the storm (cross-shard routing included)
        mismatches = 0
        for fid, digest in written.items():
            back = client.download(fid)
            if hashlib.sha256(back).hexdigest() != digest:
                mismatches += 1
                errors.append(f"readback {fid}: bytes differ")

        health = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{vport}/healthz", timeout=5).read())
        shard_view = health.get("shards", {})
        alive = shard_view.get("alive", 1 if shards == 1 else 0)

        print(json.dumps({
            "shards": shards, "alive": alive, "seconds": seconds,
            "puts": counts["put"], "gets": counts["get"],
            "errors": len(errors), "readback_mismatches": mismatches,
        }, indent=2))
        if errors:
            for e in errors[:20]:
                print("ERROR:", e, file=sys.stderr)
            return 1
        if counts["put"] == 0:
            print("ERROR: no writes completed", file=sys.stderr)
            return 1
        if shards > 1 and alive < shards:
            print(f"ERROR: /healthz reports {alive}/{shards} shards",
                  file=sys.stderr)
            return 1
        print("saturation smoke: PASS")
        return 0
    finally:
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
