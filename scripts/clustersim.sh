#!/usr/bin/env bash
# clustersim CI gate: deterministic 1000-node control-plane sweep over
# every churn scenario (steady, heat skew, node kills/flaps, rack
# loss).  Every cell runs twice and must produce an identical event-log
# digest (determinism), and fails on any control-plane contract
# violation: rebalance non-convergence, placement oscillation
# (double-move inside the cooldown window / A->B->A ping-pong),
# unbounded ring movement under churn, an unrepaired deficit, or
# balance work starving repair slots.
#
#   scripts/clustersim.sh                          # the CI budget
#   scripts/clustersim.sh --seeds 5 --nodes 2000   # deeper sweep
#   scripts/clustersim.sh --scenarios skew --seed-base 7 --json  # replay
#
# Runs beside scripts/crashsim.sh and scripts/lint.sh; JAX is not
# needed (pure control-plane python).
set -euo pipefail
cd "$(dirname "$0")/.."
exec env JAX_PLATFORMS=cpu python -m seaweedfs_tpu.clustersim \
    --seeds 2 --nodes 1000 "$@"
