#!/usr/bin/env bash
# weedlint CI gate: fails on any new finding or stale baseline entry.
#
#   scripts/lint.sh              # the CI mode (no fixes, no rewrite)
#   scripts/lint.sh --rules http-timeout,task-leak   # subset
#
# To grandfather an existing finding (new rule landing on old code):
#   python -m seaweedfs_tpu.analysis --baseline .weedlint-baseline.json \
#       --write-baseline seaweedfs_tpu/ tests/
# To suppress one deliberate site, comment the line:
#   ... # weedlint: disable=<rule>
set -euo pipefail
cd "$(dirname "$0")/.."
exec python -m seaweedfs_tpu.analysis \
    --baseline .weedlint-baseline.json "$@" seaweedfs_tpu/ tests/
