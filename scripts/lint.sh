#!/usr/bin/env bash
# weedlint CI gate: fails on any new finding or stale baseline entry.
# Runs the FULL registry — v1 single-function rules AND the v2
# inter-procedural rules (call-graph + effect summaries) — by default.
#
#   scripts/lint.sh              # the CI mode (no fixes, no rewrite)
#   scripts/lint.sh --rules http-timeout,task-leak   # subset
#   scripts/lint.sh --jobs 4     # process-pool parse, identical output
#   scripts/lint.sh --format github   # ::error annotations for CI
#
# To grandfather an existing finding (new rule landing on old code):
#   python -m seaweedfs_tpu.analysis --baseline .weedlint-baseline.json \
#       --write-baseline seaweedfs_tpu/ tests/
# To suppress one deliberate site, comment the line:
#   ... # weedlint: disable=<rule>
# weedsan (runtime) findings share the same fingerprints: the same
# suppression/baseline workflow covers them.
set -euo pipefail
cd "$(dirname "$0")/.."
exec python -m seaweedfs_tpu.analysis \
    --baseline .weedlint-baseline.json "$@" seaweedfs_tpu/ tests/
