// ThreadSanitizer harness for the native GF(2^8) kernel (role of the
// reference's `go test -race` coverage, SURVEY §5.2): N threads encode and
// reconstruct through the shared lookup tables concurrently; any data race
// in table initialization or the kernels trips TSAN.
//
// Build + run: make -C native tsan
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <thread>
#include <vector>

// the kernel sources are position-independent C functions; include them
// directly so the sanitizer instruments everything
#include "rs_core.cpp"

int main() {
    const int k = 10, m = 4, n = 1 << 16, threads = 8;
    std::vector<uint8_t> matrix(m * k);
    for (int r = 0; r < m; r++)
        for (int c = 0; c < k; c++)
            matrix[r * k + c] = (uint8_t)(r * 31 + c * 7 + 1);

    std::vector<std::thread> pool;
    for (int t = 0; t < threads; t++) {
        pool.emplace_back([&, t]() {
            std::vector<uint8_t> data(k * n), out(m * n);
            std::vector<const uint8_t*> in_rows(k);
            std::vector<uint8_t*> out_rows(m);
            for (int c = 0; c < k; c++) in_rows[c] = data.data() + c * n;
            for (int r = 0; r < m; r++) out_rows[r] = out.data() + r * n;
            for (size_t i = 0; i < data.size(); i++)
                data[i] = (uint8_t)(i * (t + 1));
            uint32_t crc = 0;
            for (int iter = 0; iter < 4; iter++) {
                gf_matrix_apply(matrix.data(), m, k, in_rows.data(),
                                out_rows.data(), n);
                // concurrent lazy-init of the crc tables is part of the
                // race surface under test
                crc = crc32c_update(crc, out.data(), out.size());
                // fold the output back in so the loop has a data dep
                for (int i = 0; i < 16; i++) data[i] ^= out[i] ^ (uint8_t)crc;
            }
        });
    }
    for (auto &th : pool) th.join();
    puts("tsan_check: ok");
    return 0;
}
