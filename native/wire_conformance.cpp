// Second-language wire conformance client.
//
// The reference proves its wire protocol with a Java client
// (other/java/client); nothing but this repo's own Python had ever
// spoken this framework's protocol. This standalone C++ program drives
// the cluster the way an external SDK would — hand-rolled HTTP/1.1 over
// raw sockets, no Python anywhere in the path:
//
//   1. GET  /dir/assign on the master        -> fid + volume URL
//   2. POST /<fid> multipart on the volume   -> size/eTag JSON
//   3. GET  /<fid>                           -> bytes must equal upload
//   4. HEAD /<fid>                           -> Content-Length == size
//   5. GET  /<fid> with If-None-Match        -> 304
//   6. GET  range bytes=4-9                  -> 206 + exact slice
//   7. DELETE /<fid>                         -> 200; GET -> 404
//   8. GET /dir/lookup?volumeId=N            -> locations JSON
//
// Exit 0 on full success; prints FAIL + reason and exits 1 otherwise.
// Usage: wire_conformance <master_host:port>
//
// Build: make -C native wire  (g++, no third-party deps)

#include <arpa/inet.h>
#include <netdb.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

namespace {

struct Response {
  int status = 0;
  std::string headers;
  std::string body;
};

[[noreturn]] void fail(const std::string& why) {
  std::fprintf(stderr, "FAIL: %s\n", why.c_str());
  std::exit(1);
}

int dial(const std::string& hostport) {
  auto colon = hostport.rfind(':');
  if (colon == std::string::npos) fail("bad address " + hostport);
  std::string host = hostport.substr(0, colon);
  std::string port = hostport.substr(colon + 1);
  addrinfo hints{};
  hints.ai_family = AF_INET;
  hints.ai_socktype = SOCK_STREAM;
  addrinfo* res = nullptr;
  if (getaddrinfo(host.c_str(), port.c_str(), &hints, &res) != 0 || !res)
    fail("resolve " + hostport);
  int fd = socket(res->ai_family, res->ai_socktype, res->ai_protocol);
  if (fd < 0) fail("socket");
  if (connect(fd, res->ai_addr, res->ai_addrlen) != 0)
    fail("connect " + hostport);
  freeaddrinfo(res);
  return fd;
}

Response request(const std::string& hostport, const std::string& method,
                 const std::string& path, const std::string& body = "",
                 const std::string& extra_headers = "") {
  int fd = dial(hostport);
  std::string req = method + " " + path + " HTTP/1.1\r\nHost: " + hostport +
                    "\r\nConnection: close\r\nContent-Length: " +
                    std::to_string(body.size()) + "\r\n" + extra_headers +
                    "\r\n" + body;
  size_t sent = 0;
  while (sent < req.size()) {
    ssize_t n = write(fd, req.data() + sent, req.size() - sent);
    if (n <= 0) fail("send");
    sent += static_cast<size_t>(n);
  }
  std::string raw;
  char buf[65536];
  ssize_t n;
  while ((n = read(fd, buf, sizeof buf)) > 0) raw.append(buf, n);
  close(fd);
  auto hdr_end = raw.find("\r\n\r\n");
  if (hdr_end == std::string::npos) fail("no header terminator");
  Response r;
  r.headers = raw.substr(0, hdr_end);
  r.body = raw.substr(hdr_end + 4);
  if (sscanf(raw.c_str(), "HTTP/1.1 %d", &r.status) != 1 &&
      sscanf(raw.c_str(), "HTTP/1.0 %d", &r.status) != 1)
    fail("bad status line: " + raw.substr(0, 40));
  return r;
}

// minimal JSON string-field extraction: "key": "value"
std::string json_str(const std::string& body, const std::string& key) {
  std::string pat = "\"" + key + "\"";
  auto at = body.find(pat);
  if (at == std::string::npos) return "";
  at = body.find('"', at + pat.size() + 1);  // opening quote of value
  if (at == std::string::npos) return "";
  auto end = body.find('"', at + 1);
  return body.substr(at + 1, end - at - 1);
}

std::string header_value(const Response& r, const std::string& name) {
  std::string lower_headers;
  for (char c : r.headers) lower_headers += std::tolower(c);
  std::string pat = "\r\n";
  for (char c : name) pat += std::tolower(c);
  pat += ":";
  auto at = lower_headers.find(pat);
  if (at == std::string::npos) return "";
  auto start = at + pat.size();
  auto end = r.headers.find("\r\n", start);
  std::string v = r.headers.substr(start, end - start);
  while (!v.empty() && v.front() == ' ') v.erase(v.begin());
  return v;
}

}  // namespace

int main(int argc, char** argv) {
  if (argc != 2) fail("usage: wire_conformance <master_host:port>");
  std::string master = argv[1];

  // 1. assign
  Response a = request(master, "GET", "/dir/assign");
  if (a.status != 200) fail("assign status " + std::to_string(a.status));
  std::string fid = json_str(a.body, "fid");
  std::string vol = json_str(a.body, "url");
  if (fid.empty() || vol.empty()) fail("assign fields: " + a.body);
  std::printf("assign: fid=%s url=%s\n", fid.c_str(), vol.c_str());

  // 2. multipart upload
  std::string payload;
  for (int i = 0; i < 1000; i++) payload += "cpp-wire-";
  std::string bnd = "cppwirebnd";
  std::string mp = "--" + bnd +
                   "\r\nContent-Disposition: form-data; name=\"file\"; "
                   "filename=\"c.bin\"\r\nContent-Type: "
                   "application/octet-stream\r\n\r\n" +
                   payload + "\r\n--" + bnd + "--\r\n";
  Response up = request(vol, "POST", "/" + fid, mp,
                        "Content-Type: multipart/form-data; boundary=" +
                            bnd + "\r\n");
  if (up.status != 201) fail("upload status " + std::to_string(up.status));
  std::string etag = json_str(up.body, "eTag");
  if (etag.empty()) fail("upload eTag: " + up.body);
  std::printf("upload: eTag=%s\n", etag.c_str());

  // 3. read back
  Response g = request(vol, "GET", "/" + fid);
  if (g.status != 200) fail("get status " + std::to_string(g.status));
  if (g.body != payload)
    fail("payload mismatch: got " + std::to_string(g.body.size()) +
         " bytes, want " + std::to_string(payload.size()));
  std::printf("get: %zu bytes identical\n", g.body.size());

  // 4. HEAD
  Response h = request(vol, "HEAD", "/" + fid);
  if (h.status != 200) fail("head status " + std::to_string(h.status));
  if (header_value(h, "Content-Length") != std::to_string(payload.size()))
    fail("head content-length " + header_value(h, "Content-Length"));
  if (!h.body.empty()) fail("head returned a body");

  // 5. conditional GET
  Response c =
      request(vol, "GET", "/" + fid, "",
              "If-None-Match: \"" + etag + "\"\r\n");
  if (c.status != 304) fail("if-none-match status " +
                            std::to_string(c.status));

  // 6. range
  Response rg = request(vol, "GET", "/" + fid, "", "Range: bytes=4-9\r\n");
  if (rg.status != 206) fail("range status " + std::to_string(rg.status));
  if (rg.body != payload.substr(4, 6)) fail("range bytes: " + rg.body);

  // 7. delete
  Response d = request(vol, "DELETE", "/" + fid);
  if (d.status != 200) fail("delete status " + std::to_string(d.status));
  Response gone = request(vol, "GET", "/" + fid);
  if (gone.status != 404) fail("post-delete status " +
                               std::to_string(gone.status));

  // 8. lookup
  std::string vid = fid.substr(0, fid.find(','));
  Response lk = request(master, "GET", "/dir/lookup?volumeId=" + vid);
  if (lk.status != 200) fail("lookup status " + std::to_string(lk.status));
  if (lk.body.find("locations") == std::string::npos)
    fail("lookup body: " + lk.body);

  std::printf("WIRE CONFORMANCE PASS\n");
  return 0;
}
