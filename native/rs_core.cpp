// Native CPU core for the TPU-native store: GF(2^8) Reed-Solomon bulk math
// and CRC32C. This is the build's replacement for the reference's native
// dependencies (klauspost/reedsolomon SIMD assembly and klauspost/crc32,
// see seaweedfs go.mod:44-45): the CPU-side ErasureCoder backend used for
// bit-identity cross-checks against the TPU kernels and for hosts without a
// chip.
//
// Field: GF(2^8), polynomial 0x11D, generator 2 — same arithmetic as
// gf256.py; matrices are built in Python and passed in, so all backends
// share one construction.
//
// The hot loop is a split-nibble table kernel (the same algorithmic shape
// klauspost's AVX2 galMulSlice uses, expressed portably so the compiler can
// auto-vectorize with -O3 -march=native).

#include <cstddef>
#include <cstdint>
#include <cstring>
#include <mutex>

#if defined(__AVX2__)
#include <immintrin.h>
#endif

namespace {

constexpr int kFieldPoly = 0x11D;

struct Tables {
    // mul[a][b] = a*b in GF(2^8)
    uint8_t mul[256][256];
    Tables() {
        uint8_t exp[512];
        int log[256] = {0};
        int x = 1;
        for (int i = 0; i < 255; i++) {
            exp[i] = static_cast<uint8_t>(x);
            log[x] = i;
            x <<= 1;
            if (x & 0x100) x ^= kFieldPoly;
        }
        for (int i = 255; i < 512; i++) exp[i] = exp[i - 255];
        for (int a = 0; a < 256; a++) {
            for (int b = 0; b < 256; b++) {
                mul[a][b] = (a && b)
                    ? exp[log[a] + log[b]]
                    : 0;
            }
        }
    }
};

const Tables& tables() {
    static const Tables t;
    return t;
}

// out ^= coeff * in, over n bytes, via low/high nibble tables
void mul_add_row(uint8_t coeff, const uint8_t* in, uint8_t* out, size_t n) {
    if (coeff == 0) return;
    const auto& mul = tables().mul;
    if (coeff == 1) {
        for (size_t i = 0; i < n; i++) out[i] ^= in[i];
        return;
    }
    alignas(32) uint8_t lo[16], hi[16];
    for (int v = 0; v < 16; v++) {
        lo[v] = mul[coeff][v];
        hi[v] = mul[coeff][v << 4];
    }
    size_t i = 0;
#if defined(__AVX2__)
    // 32 bytes per step: product = pshufb(lo, b&0xF) ^ pshufb(hi, b>>4)
    const __m256i vlo = _mm256_broadcastsi128_si256(
        _mm_load_si128(reinterpret_cast<const __m128i*>(lo)));
    const __m256i vhi = _mm256_broadcastsi128_si256(
        _mm_load_si128(reinterpret_cast<const __m128i*>(hi)));
    const __m256i mask = _mm256_set1_epi8(0x0F);
    for (; i + 32 <= n; i += 32) {
        __m256i b = _mm256_loadu_si256(
            reinterpret_cast<const __m256i*>(in + i));
        __m256i bl = _mm256_and_si256(b, mask);
        __m256i bh = _mm256_and_si256(_mm256_srli_epi64(b, 4), mask);
        __m256i prod = _mm256_xor_si256(_mm256_shuffle_epi8(vlo, bl),
                                        _mm256_shuffle_epi8(vhi, bh));
        __m256i o = _mm256_loadu_si256(
            reinterpret_cast<const __m256i*>(out + i));
        _mm256_storeu_si256(reinterpret_cast<__m256i*>(out + i),
                            _mm256_xor_si256(o, prod));
    }
#endif
    for (; i < n; i++) {
        uint8_t b = in[i];
        out[i] ^= static_cast<uint8_t>(lo[b & 0x0F] ^ hi[b >> 4]);
    }
}

}  // namespace

extern "C" {

// outputs[r] = sum_c matrix[r*cols+c] * inputs[c]  (GF(2^8), n bytes each).
// Column-blocked so each (input, output) slice stays L2-resident while all
// rows x cols coefficient passes run over it.
void gf_matrix_apply(const uint8_t* matrix, int rows, int cols,
                     const uint8_t* const* inputs, uint8_t* const* outputs,
                     size_t n) {
    constexpr size_t kBlock = 64 * 1024;
    for (size_t off = 0; off < n; off += kBlock) {
        size_t len = n - off < kBlock ? n - off : kBlock;
        for (int r = 0; r < rows; r++) {
            std::memset(outputs[r] + off, 0, len);
            for (int c = 0; c < cols; c++) {
                mul_add_row(matrix[r * cols + c], inputs[c] + off,
                            outputs[r] + off, len);
            }
        }
    }
}

// ---- CRC32C (Castagnoli), slice-by-8, matching Go crc32.Update semantics ----

static uint32_t crc32c_table[8][256];
static std::once_flag crc32c_once;

static void crc32c_fill() {
    const uint32_t poly = 0x82F63B78u;  // reflected Castagnoli
    for (uint32_t i = 0; i < 256; i++) {
        uint32_t crc = i;
        for (int j = 0; j < 8; j++) {
            crc = (crc & 1) ? (crc >> 1) ^ poly : crc >> 1;
        }
        crc32c_table[0][i] = crc;
    }
    for (uint32_t i = 0; i < 256; i++) {
        uint32_t crc = crc32c_table[0][i];
        for (int k = 1; k < 8; k++) {
            crc = crc32c_table[0][crc & 0xFF] ^ (crc >> 8);
            crc32c_table[k][i] = crc;
        }
    }
}

// concurrent first use must not race the table fill (TSAN-checked by
// native/tsan_check.cpp)
static void crc32c_init() { std::call_once(crc32c_once, crc32c_fill); }

uint32_t crc32c_update(uint32_t crc, const uint8_t* data, size_t n) {
    crc32c_init();
    crc = ~crc;
    while (n >= 8) {
        crc ^= static_cast<uint32_t>(data[0]) |
               (static_cast<uint32_t>(data[1]) << 8) |
               (static_cast<uint32_t>(data[2]) << 16) |
               (static_cast<uint32_t>(data[3]) << 24);
        uint32_t hi = static_cast<uint32_t>(data[4]) |
                      (static_cast<uint32_t>(data[5]) << 8) |
                      (static_cast<uint32_t>(data[6]) << 16) |
                      (static_cast<uint32_t>(data[7]) << 24);
        crc = crc32c_table[7][crc & 0xFF] ^
              crc32c_table[6][(crc >> 8) & 0xFF] ^
              crc32c_table[5][(crc >> 16) & 0xFF] ^
              crc32c_table[4][crc >> 24] ^
              crc32c_table[3][hi & 0xFF] ^
              crc32c_table[2][(hi >> 8) & 0xFF] ^
              crc32c_table[1][(hi >> 16) & 0xFF] ^
              crc32c_table[0][hi >> 24];
        data += 8;
        n -= 8;
    }
    while (n--) {
        crc = crc32c_table[0][(crc ^ *data++) & 0xFF] ^ (crc >> 8);
    }
    return ~crc;
}

// masked needle checksum (reference weed/storage/needle/crc.go:23-25)
uint32_t crc32c_needle_value(uint32_t crc) {
    uint32_t rot = (crc >> 15) | (crc << 17);
    return rot + 0xA282EAD8u;
}

}  // extern "C"
