#!/usr/bin/env python3
"""Headline benchmark: the RS(10,4) ec.encode PIPELINE on one chip.

Round-1 benched only the kernel on pre-staged HBM arrays; the north star
(BASELINE config 1/2) is the full `.dat` -> `.ec00-13` encode path the
servers actually run. This bench measures, in order:

  pipeline   stream_encode of a >=1GB synthetic volume at the reference
             geometry (1MB small-block stripes for a 1GB volume — the exact
             layout ec_encoder.go:194-231 produces), overlapped disk read /
             host->HBM / Pallas kernel / 14-way shard write-back
             (seaweedfs_tpu/ec/pipeline.py). This is the headline metric.
  kernel     the fused Pallas GF(2^8) kernel on resident data (the on-TPU
             portion; BASELINE target >=20 GB/s/chip)
  rebuild    stream_rebuild of 4 missing shards from 10 survivors, p50 over
             repetitions (BASELINE config 3)
  sweep      kernel encode GB/s at RS(6,3)/(12,4)/(20,4) (BASELINE config 4)

Prints exactly one JSON line:
  {"metric": ..., "value": N, "unit": "GB/s", "vs_baseline": N, "extra": {...}}
vs_baseline is pipeline GB/s over the 20 GB/s/chip north-star target.
"""

import json
import os
import shutil
import statistics
import sys
import tempfile
import time

import numpy as np

BASELINE_GBPS = 20.0  # BASELINE.json: ec.encode >= 20 GB/s/chip on v5e

# soft time budgets for the degraded-tunnel case (one policy, two stages):
# past REBUILD_BUDGET_S the rebuild loop keeps only its first timed rep;
# past SOFT_BUDGET_S the optional sweep/fused phases are skipped
REBUILD_BUDGET_S = 420.0
SOFT_BUDGET_S = 560.0


def _make_volume(path: str, size: int) -> None:
    rng = np.random.default_rng(7)
    with open(path, "wb") as f:
        left = size
        while left > 0:
            n = min(left, 64 * 1024 * 1024)
            f.write(rng.integers(0, 256, n, dtype=np.uint8).tobytes())
            left -= n


def measure_link() -> tuple[float, float]:
    """Host<->device link bandwidth (GB/s). On tunneled single-chip dev
    environments (axon) the device->host direction can be orders of
    magnitude slower than HBM — it caps any pipeline that must land parity
    bytes on host disk, so it is measured and reported explicitly."""
    import jax
    x = np.zeros(64 * 1024 * 1024, dtype=np.uint8)
    d = jax.device_put(x)
    d.block_until_ready()
    t0 = time.perf_counter()
    d = jax.device_put(x)
    d.block_until_ready()
    h2d = x.nbytes / (time.perf_counter() - t0) / 1e9
    np.asarray(d)  # first fetch may include warmup
    e = jax.device_put(np.ones_like(x))
    e.block_until_ready()
    t0 = time.perf_counter()
    np.asarray(e)
    d2h = x.nbytes / (time.perf_counter() - t0) / 1e9
    return h2d, d2h


def bench_fused(work: str, coder, vol_size: int) -> dict:
    """BASELINE config 5: compaction + gzip + RS(10,4) in one pass over a
    needle volume that is ~50% garbage."""
    from seaweedfs_tpu.ec.fused import fused_vacuum_gzip_encode
    from seaweedfs_tpu.storage.needle import Needle
    from seaweedfs_tpu.storage.volume import Volume

    vdir = os.path.join(work, "fusedvol")
    os.makedirs(vdir, exist_ok=True)
    v = Volume(vdir, "", 7, create=True)
    needle_data = (b"fused bench payload: compressible text block. " * 450)
    target = min(vol_size // 8, 64 * 1024 * 1024)
    count = max(target // len(needle_data), 10)
    for i in range(1, count + 1):
        v.write_needle(Needle(cookie=i, id=i, data=needle_data))
    for i in range(1, count + 1, 2):
        v.delete_needle(Needle(cookie=i, id=i))
    src_bytes = v.data_file_size()
    dst = os.path.join(vdir, "out_7")
    t0 = time.perf_counter()
    out = fused_vacuum_gzip_encode(v, dst, coder)
    dt = time.perf_counter() - t0
    v.close()
    return {"src_bytes": src_bytes,
            "compacted_bytes": out["compacted_bytes"],
            "gbps": round(src_bytes / dt / 1e9, 3)}


def bench_kernel(k: int, m: int, n: int, reps: int):
    import jax
    import jax.numpy as jnp
    from seaweedfs_tpu.ops import gf256, rs_jax, rs_pallas

    data = jnp.asarray(
        np.random.default_rng(0).integers(0, 256, (k, n), dtype=np.uint8))
    if jax.default_backend() == "tpu":
        fn = rs_pallas.gf_apply_pallas(gf256.parity_matrix(k, m))
    else:
        # pallas interpret mode is a pure-python emulator — useless for
        # timing; the XLA bitplane path is the honest CPU kernel
        fn = jax.jit(rs_jax.gf_apply_bitplane(gf256.parity_matrix(k, m)))
    out = fn(data)
    out.block_until_ready()  # compile + warm

    # correctness gate: never report speed for wrong parity
    check = np.asarray(out[:, :65536])
    want = gf256.encode_parity(np.asarray(data[:, :65536]), m)
    if not np.array_equal(check, want):
        raise AssertionError(f"parity mismatch at RS({k},{m})")

    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(data)
    out.block_until_ready()
    dt = (time.perf_counter() - t0) / reps
    return (k * n) / dt / 1e9


def main() -> None:
    import jax

    from seaweedfs_tpu import ec
    from seaweedfs_tpu.ec import pipeline

    backend = jax.default_backend()
    on_tpu = backend == "tpu"
    # CPU fallback keeps the bench runnable in dev; the recorded numbers
    # come from the driver's TPU run. The TPU volume size is picked so the
    # shard size is an exact multiple of the batch width: a single kernel
    # shape compiles once (1120MiB -> 112 small rows -> 112MiB shards =
    # 7 x 16MiB batches).
    vol_size = (1120 * 1024 * 1024) if on_tpu else (16 * 1024 * 1024)
    kernel_n = (64 * 1024 * 1024) if on_tpu else (1024 * 1024)
    kernel_reps = 10 if on_tpu else 3
    rebuild_reps = 2 if on_tpu else 1
    batch = 16 * 1024 * 1024 if on_tpu else 1024 * 1024

    h2d_gbps, d2h_gbps = measure_link()
    if on_tpu:
        coder = ec.get_coder("pallas", 10, 4)
    else:
        try:
            coder = ec.get_coder("cpp", 10, 4)
        except Exception:
            coder = ec.get_coder("jax", 10, 4)
    work = tempfile.mkdtemp(prefix="swfs_bench_")
    try:
        _run_configs(work, coder, vol_size, kernel_n, kernel_reps,
                     rebuild_reps, batch, backend, h2d_gbps, d2h_gbps)
    except AssertionError as e:
        # keep the one-JSON-line contract even for correctness failures
        print(json.dumps({
            "metric": "ec.encode pipeline GB/s/chip (.dat -> .ec00-13)",
            "value": 0.0, "unit": "GB/s", "vs_baseline": 0.0,
            "error": str(e)}))
        sys.exit(1)
    finally:
        shutil.rmtree(work, ignore_errors=True)


def _phase(name: str, t0: float) -> float:
    now = time.perf_counter()
    print(f"[bench] {name}: {now - t0:.1f}s", file=sys.stderr, flush=True)
    return now


def _run_configs(work, coder, vol_size, kernel_n, kernel_reps, rebuild_reps,
                 batch, backend, h2d_gbps, d2h_gbps) -> None:
    from seaweedfs_tpu import ec
    from seaweedfs_tpu.ec import pipeline

    started = time.perf_counter()
    t = started
    base = os.path.join(work, "1")
    _make_volume(base + ".dat", vol_size)
    t = _phase("volume gen", t)

    # run 1 warms every kernel shape (batch + tail widths); run 2 is
    # the steady-state measurement
    pipeline.stream_encode(base, coder, batch_size=batch)
    t = _phase("encode warm (compile)", t)
    for i in range(14):
        os.remove(base + ec.to_ext(i))
    t0 = time.perf_counter()
    pipeline.stream_encode(base, coder, batch_size=batch)
    pipeline_dt = time.perf_counter() - t0
    pipeline_gbps = vol_size / pipeline_dt / 1e9
    t = _phase("encode timed", t)

    # rebuild p50 (config 3): 4 missing shards from 10 survivors;
    # one untimed warm pass compiles the reconstruction kernel
    victims = [0, 3, 7, 12]
    times = []
    for rep in range(rebuild_reps + 1):
        for v in victims:
            os.remove(base + ec.to_ext(v))
        t0 = time.perf_counter()
        pipeline.stream_rebuild(base, coder, batch_size=batch)
        if rep > 0:
            times.append(time.perf_counter() - t0)
        if rep >= 1 and time.perf_counter() - started > REBUILD_BUDGET_S:
            break  # degraded link: one timed rep is enough
    rebuild_p50 = statistics.median(times)
    shard_size = os.path.getsize(base + ec.to_ext(0))
    t = _phase(f"rebuild x{len(times) + 1}", t)

    kernel_gbps = bench_kernel(10, 4, kernel_n, kernel_reps)
    t = _phase("kernel 10,4", t)

    # the dev chip's tunnel degrades unpredictably under sustained load;
    # optional phases yield once the soft budget is spent so the bench
    # always emits its JSON line well inside the driver's patience
    soft_deadline = started + SOFT_BUDGET_S
    sweep = {}
    for (k, m) in ((6, 3), (12, 4), (20, 4)):
        if time.perf_counter() > soft_deadline:
            sweep[f"{k},{m}"] = None  # skipped (time budget); type-stable
            continue
        n = kernel_n - kernel_n % (16384 * 8)
        sweep[f"{k},{m}"] = round(bench_kernel(k, m, n, kernel_reps), 2)
        t = _phase(f"kernel sweep {k},{m}", t)

    if time.perf_counter() > soft_deadline:
        fused = {"skipped": True}
    else:
        fused = bench_fused(work, coder, vol_size)
        t = _phase("fused pipeline", t)

    print(json.dumps({
        "metric": "ec.encode pipeline GB/s/chip (.dat -> .ec00-13)",
        "value": round(pipeline_gbps, 2),
        "unit": "GB/s",
        "vs_baseline": round(pipeline_gbps / BASELINE_GBPS, 3),
        "extra": {
            "backend": backend,
            "volume_bytes": vol_size,
            "kernel_gbps": round(kernel_gbps, 2),
            "kernel_vs_target": round(kernel_gbps / BASELINE_GBPS, 3),
            "rebuild_p50_s": round(rebuild_p50, 3),
            "rebuild_reps_used": len(times),
            "rebuild_gbps": round(
                10 * shard_size / rebuild_p50 / 1e9, 2),
            "sweep_kernel_gbps": sweep,
            "fused_compact_gzip_rs": fused,
            "link_h2d_gbps": round(h2d_gbps, 3),
            "link_d2h_gbps": round(d2h_gbps, 3),
            "note": ("pipeline includes disk read, host<->device transfer "
                     "and 14-way shard write-back; on a tunneled dev chip "
                     "the device->host link (link_d2h_gbps) bounds it, "
                     "since m/k of the volume (parity) must return to "
                     "host disk. kernel_gbps is the on-TPU portion."),
        },
    }))


if __name__ == "__main__":
    main()
