#!/usr/bin/env python3
"""Headline benchmark: the RS(10,4) ec.encode PIPELINE on one chip.

Round-4 architecture: the parent orchestrates PHASES, each TPU phase in
its OWN subprocess, and assembles exactly one JSON line at the end.
Three tunneled-dev-chip facts force the shape (all measured):

  * ONE device->host read — even 16 bytes — flips the process's
    transfer path into a ~100x degraded mode (1.7 -> 0.015 GB/s H2D)
    for the REST of the process. Fresh processes start healthy, so each
    TPU phase gets its own subprocess and defers every D2H (including
    the digest materialize) until after all staging;
  * some remote compiles trigger the same degradation, so phases
    compile lazily at dispatch time, after staging;
  * a compiled executable's FIRST execution pays a one-time program
    load (~40-100s through the tunnel); steady-state re-execution is
    ~0.13s for a 1.1GB window. The cold pass carries compile+load; the
    steady-state reps carry the honest per-volume number.

Phases / BASELINE configs:
  encode   config 1/2: staged-window device-sink pipeline, digest-
           verified vs an independent host coder; ledger of measured
           components (read/stage/execute/materialize) + steady-state
           per-volume rate (config 2's program-reuse regime) + healthy-
           link projection from the measured parts
  rebuild  config 3: same protocol over stream_rebuild_device_sink
           (4 victims from 10 survivors), digest vs the real shard files
  kernel   pinned RS(10,4) Pallas kernel + RS(k,m) sweep (config 4) +
           tile sweep, ordered so every config reports >=1 number
  fused    config 5: compaction + gzip + RS with per-phase seconds
  system   req/s vs the reference's published benchmark (README.md:504)
  needle_map  disk-backed index numbers

Prints one JSON line:
  {"metric": ..., "value": N, "unit": "GB/s", "vs_baseline": N, "extra"}
"""

import json
import os
import shutil
import statistics
import subprocess
import sys
import tempfile
import time

import numpy as np

BASELINE_GBPS = 20.0  # BASELINE.json: ec.encode >= 20 GB/s/chip on v5e

HARD_BUDGET_S = 1400.0  # the rec-window compile+load alone can take 400s
MB = 1024 * 1024

# encode volume: shard width divides the batch width exactly so one
# window shape covers the whole volume (10 x 16MB batches x 7)
VOL_BYTES = 1120 * MB
BATCH_W = 16 * MB          # per-row width -> 160MB per staged batch
VICTIMS = [0, 3, 7, 12]


def _make_volume(path: str, size: int) -> None:
    rng = np.random.default_rng(7)
    with open(path, "wb") as f:
        left = size
        while left > 0:
            n = min(left, 64 * MB)
            f.write(rng.integers(0, 256, n, dtype=np.uint8).tobytes())
            left -= n


def _host_coder():
    from seaweedfs_tpu import ec
    try:
        return ec.get_coder("cpp", 10, 4)
    except Exception:
        return ec.get_coder("numpy", 10, 4)


def measure_link() -> dict:
    """Host->device bandwidth on THIS process's fresh tunnel
    (incompressible data, 1-D array). Deliberately does NO device->host
    read: a single D2H — even 16 bytes — flips the tunnel's transfer
    path into a ~100x degraded mode for the rest of the process
    (measured), which is exactly what poisoned two whole bench runs.
    D2H latency is reported from the pipeline ledger's wait_s instead
    (the final 16-byte digest materialize)."""
    import jax
    x = np.random.default_rng(3).integers(0, 256, 64 * MB, dtype=np.uint8)
    d = jax.device_put(x)
    d.block_until_ready()  # warm
    t0 = time.perf_counter()
    d = jax.device_put(x)
    d.block_until_ready()
    h2d = x.nbytes / (time.perf_counter() - t0) / 1e9
    return {"h2d_gbps": round(h2d, 3)}


def _warm_stage(shape: tuple) -> None:
    """Warm the exact 2-D staging shape: the tunnel charges a cold-path
    penalty per array shape (first [10, W] put runs ~7x slower than the
    steady rate), which would otherwise be billed to the first batch."""
    import jax
    z = np.zeros(shape, dtype=np.uint8)
    for _ in range(2):
        h = jax.device_put(z)
        h.block_until_ready()


# ----------------------------------------------------------------- phases

def _phase_checkpoint(work: str, name: str, out: dict) -> None:
    """Atomically persist a phase's partial record NOW. The driver reads
    <name>_partial.json when the phase times out or dies, so a wedged
    sub-step (the rebuild window compile through a degraded tunnel) can
    no longer null every number the phase already measured."""
    try:
        path = os.path.join(work, f"{name}_partial.json")
        with open(path + ".tmp", "w") as f:
            json.dump(out, f)
        os.replace(path + ".tmp", path)
    except OSError:
        pass


def _load_partial(work: str, name: str) -> dict:
    try:
        with open(os.path.join(work, f"{name}_partial.json")) as f:
            d = json.load(f)
        d["partial"] = True
        return d
    except Exception:
        return {}


def phase_encode(work: str) -> dict:
    """Config 1/2: the staged-window encode sink, fresh process.

    Round 10: the steady state is measured over R full DISK->chip
    re-feeds through the parallel host-feed tier (reader pool prefaults
    pages concurrently, a stager pool keeps several H2D puts in flight)
    with window dispatches pipelined across volumes — the multi-volume
    encode-queue regime the production pipeline now runs
    (pipeline.stream_encode_many). All re-feeds happen BEFORE the first
    device->host read: one D2H flips this tunnel ~100x degraded."""
    import jax

    from seaweedfs_tpu import ec
    from seaweedfs_tpu.ec import pipeline

    # force real parallelism even where cpu_count reports 1: the stages
    # being overlapped are IO-bound (disk faults, tunnel copies), so
    # extra threads add outstanding IOs, not CPU contention
    READERS = max(2, min(4, os.cpu_count() or 1))
    STAGERS = max(2, min(4, os.cpu_count() or 1))

    out: dict = {"backend": jax.default_backend(),
                 "feed": {"readers": READERS, "stagers": STAGERS}}
    out["link"] = measure_link()

    base = os.path.join(work, "1")

    # pallas on a real chip (the window executable pipelines at 41 GB/s
    # vs the XLA bitplane path's 36 — probe round 5); jax elsewhere
    # (pallas interpret mode is far too slow for a 1.1GB volume)
    coder = ec.get_coder(
        "pallas" if jax.default_backend() == "tpu" else "jax", 10, 4)
    # NO ahead-of-time compile here: staging needs no program, and on
    # this tunnel even a chipless remote compile can flip the transfer
    # path into its degraded mode (measured on the reconstruction
    # program). The window dispatch compiles lazily AFTER staging; the
    # cold pass therefore includes compile + one-time program load, and
    # the steady-state reps below carry the honest per-volume number.
    _warm_stage((10, BATCH_W))
    stats: dict = {}
    t0 = time.perf_counter()
    saved: dict = {}
    orig = coder.encode_digest_window_async

    def capture(staged, acc=None):
        saved["staged"] = staged
        return orig(staged, acc)

    coder.encode_digest_window_async = capture
    # materialize=False: the cold digest's 16-byte D2H would flip the
    # tunnel degraded BEFORE the steady-state re-feeds below — hold the
    # on-device acc and verify it with the other digests after the loop
    acc_cold = pipeline.stream_encode_device_sink(
        base, coder, batch_size=BATCH_W, window_bytes=2 * VOL_BYTES,
        stats=stats, stagers=STAGERS, readers=READERS,
        materialize=False)
    block = getattr(acc_cold, "block_until_ready", None)
    if block is not None:
        block()
    cold_total = time.perf_counter() - t0
    out["ledger"] = stats
    out["cold_pass_s"] = round(cold_total, 2)  # includes program load
    _phase_checkpoint(work, "encode", out)

    # ground truth from an independent host implementation (HOST coder:
    # no device work, no D2H) — computed AFTER the timed staging so its
    # full-volume read + host encode (~2s of cache/CPU churn) cannot
    # perturb the measurement
    t0 = time.perf_counter()
    want = pipeline.stream_encode_device_sink(
        base, _host_coder(), batch_size=BATCH_W, window_bytes=2 * VOL_BYTES)
    out["host_digest_s"] = round(time.perf_counter() - t0, 2)

    # steady state, round 10: R full disk -> host -> HBM -> kernel
    # re-feeds back-to-back through the parallel feed tier with
    # materialization deferred (multi-volume window batching: volume
    # N+1's reads/stages overlap volume N's window execution). Runs
    # BEFORE any D2H so the tunnel stays healthy for every rep; digests
    # verify after the loop.
    R2 = 3
    rep_stats: list = []
    accs: list = []
    t0 = time.perf_counter()
    for _ in range(R2):
        st: dict = {}
        accs.append(pipeline.stream_encode_device_sink(
            base, coder, batch_size=BATCH_W, window_bytes=2 * VOL_BYTES,
            stats=st, materialize=False, stagers=STAGERS,
            readers=READERS))
        rep_stats.append(st)
    block = getattr(accs[-1], "block_until_ready", None)
    if block is not None:
        block()  # device executes in dispatch order
    refeed_wall = time.perf_counter() - t0
    per_volume_s = refeed_wall / R2
    out["steady_state_volume_s"] = round(per_volume_s, 3)
    out["steady_state_reps"] = R2
    out["value_gbps"] = round(VOL_BYTES / per_volume_s / 1e9, 2)
    out["refeed_ledgers"] = rep_stats
    _phase_checkpoint(work, "encode", out)

    # in-window execution rate: the program is loaded, data staged —
    # re-execute, PIPELINED (config 2's program-reuse regime). A single
    # dispatch+block instead measures the tunnel's per-sync round-trip.
    R = 5
    acc_r = None
    t0 = time.perf_counter()
    for _ in range(R):
        acc_r = orig(saved["staged"], acc_r)
    acc_r.block_until_ready()
    exec_s = (time.perf_counter() - t0) / R
    out["exec_steady_s"] = round(exec_s, 4)
    out["exec_steady_reps"] = R
    # --- first D2H below: the tunnel may degrade from here on; every
    # rate above is already measured and checkpointed ---
    d_cold = np.asarray(coder.materialize(acc_cold), dtype=np.uint32)
    if d_cold.tolist() != want.tolist():
        raise AssertionError(f"sink digest {d_cold} != host {want}")
    # after R chained windows over the same data the wrapping digest is
    # R * want mod 2^32 — a correctness check on the pipelined loop
    d2 = np.asarray(coder.materialize(acc_r), dtype=np.uint32)
    want_r = (want.astype(np.uint64) * R & 0xFFFFFFFF).astype(np.uint32)
    if d2.tolist() != want_r.tolist():
        raise AssertionError("pipelined steady digest mismatch")
    # every re-feed's digest must equal the host digest (fresh acc per
    # rep): the steady-state loop provably performed the full encode
    for a in accs:
        d = np.asarray(coder.materialize(a), dtype=np.uint32)
        if d.tolist() != want.tolist():
            raise AssertionError(f"re-feed digest {d} != host {want}")
    # per-rep sync cost, reported for transparency (latency, not rate)
    t0 = time.perf_counter()
    acc1 = orig(saved["staged"])
    d1 = np.asarray(coder.materialize(acc1), dtype=np.uint32)
    out["single_rep_sync_s"] = round(time.perf_counter() - t0, 4)
    if d1.tolist() != want.tolist():
        raise AssertionError("steady-state digest mismatch")

    # measured feed-stage breakdown, one number per pipeline stage, so
    # future rounds see which stage binds without re-deriving it from the
    # ledger (write is None here: the device sink writes no shard files);
    # medians over the steady-state re-feeds
    def med(key: str) -> float:
        vals = sorted(s.get(key) or 0.0 for s in rep_stats)
        return vals[len(vals) // 2]

    read_s, h2d_s = med("read_wait_s"), med("stage_s")
    out["feed_stages_s"] = {
        "read": round(read_s, 3),
        "h2d": round(h2d_s, 3),
        "kernel": round(exec_s, 4),
        "write": None,
    }
    _phase_checkpoint(work, "encode", out)

    # arithmetic bound from measured parts: the pipeline cannot beat its
    # slowest stage; on a healthy host H2D is not the binding stage
    stage_gbps = (VOL_BYTES / h2d_s / 1e9) if h2d_s > 1e-3 else None
    kernel_gbps = VOL_BYTES / exec_s / 1e9
    disk_gbps = (VOL_BYTES / read_s / 1e9) if read_s > 1e-3 else None
    out["component_rates_gbps"] = {
        "disk_read": round(disk_gbps, 2) if disk_gbps else None,
        "h2d_stage": round(stage_gbps, 2) if stage_gbps else None,
        "kernel_window": round(kernel_gbps, 2),
    }
    # chip-side capability (the BASELINE north star is GB/s/CHIP): the
    # window executable — H2D-fed compute incl. the digest reduction —
    # measured with pipelined dispatches. Host-side stages are reported
    # separately: the reader pool + stager pool now overlap disk reads
    # with the H2D copies (the old 1-core serial feed is gone), and H2D
    # here is the tunnel, not a PCIe/DMA link.
    out["chip_encode_gbps"] = round(kernel_gbps, 2)
    healthy = {
        f"disk_read (reader pool x{READERS})": disk_gbps,
        "kernel_window (chip)": kernel_gbps,
    }
    healthy = {k: v for k, v in healthy.items() if v}
    if healthy:
        binding = min(healthy, key=healthy.get)
        out["healthy_link_projection_gbps"] = round(healthy[binding], 2)
        out["healthy_link_binding_stage"] = binding
    else:
        out["healthy_link_projection_gbps"] = None
    _phase_checkpoint(work, "encode", out)

    # LAST, after every measurement: AOT-compile the dynamic-matrix
    # window program into the persistent compilation cache. It is the
    # SAME executable the rebuild phase dispatches (encode and rec
    # windows share it, ec/coder.py), so phase_rebuild's historically
    # wedge-prone cold compile becomes a disk-cache hit. Compiling here
    # can degrade this process's tunnel — which no longer matters, the
    # phase is done measuring.
    try:
        n_batches = -(-VOL_BYTES // (10 * BATCH_W))
        ec.get_coder("jax", 10, 4).warm_encode_digest_window(
            n_batches, (10, BATCH_W))
        out["rebuild_cache_warmed"] = True
    except Exception as e:  # advisory: rebuild still runs, just colder
        out["rebuild_cache_warmed"] = False
        out["warm_cache_error"] = str(e)[:300]
    return out


def phase_rebuild(work: str, budget_s: float = 580.0) -> dict:
    """Config 3: reconstruction digest sink + batch amortization, fresh
    process. Shard files must already exist in `work`.

    Tunnel-critical schedule: the RECONSTRUCTION window compile is one of
    the remote compiles that flips this process's H2D path ~100x slower
    (memory/verify notes, measured round 4) — so ALL staging for every
    volume in the batch happens BEFORE the first dispatch, and every
    materialize (D2H) happens after the last dispatch.

    Wedge guards (round 6): the rec window now reuses the ENCODE
    program — the dynamic-matrix window executable (ec/coder.py) is the
    same compiled program for encode and reconstruction, and the shared
    persistent compilation cache (_run_phase) carries it across the
    phase boundary — plus WEED_EC_REC_WINDOW_BATCHES caps the window.
    Every measured value checkpoints to rebuild_partial.json the moment
    it exists, so even a wedged sub-step leaves real numbers, and
    optional sub-steps are skipped when the phase budget runs low."""
    import jax

    from seaweedfs_tpu import ec
    from seaweedfs_tpu.ec import feed as feed_mod
    from seaweedfs_tpu.ec import pipeline

    started = time.perf_counter()

    def left() -> float:
        return budget_s - (time.perf_counter() - started)

    out: dict = {"backend": jax.default_backend(), "victims": VICTIMS,
                 "digest_verified": False}

    def ckpt() -> None:
        _phase_checkpoint(work, "rebuild", out)

    # checkpoint from second zero: a wedge ANYWHERE (BENCH_r05 recorded
    # only {"error": ...} because the phase died before its first
    # checkpoint) must still leave a partial record for the driver
    ckpt()
    base = os.path.join(work, "1")
    want = pipeline.shard_file_digest(base, VICTIMS)

    shard_size = os.path.getsize(base + ec.to_ext(0))
    out["shard_size"] = shard_size
    ckpt()

    # jax (XLA bitplane) coder here: its rec-window program is the one
    # round 4 proved completes through this tunnel. The pallas rec
    # window was measured in round 5 to wedge the phase (its compile
    # degrades the process's transfer path and the program load then
    # crawls on the degraded link); the pipelined XLA window still runs
    # at ~35 GB/s, on par with the pinned pallas kernel.
    coder = ec.get_coder("jax", 10, 4)

    present = [i for i in range(14) if i not in VICTIMS]
    survivors = tuple(present[:10])
    READERS = max(2, min(4, os.cpu_count() or 1))
    src = feed_mod.ShardFeed([base + ec.to_ext(i) for i in survivors],
                             BATCH_W, pooled=False, readers=READERS)

    def read_batches() -> list:
        """7 x [k, 16MB] batches per volume — the round-4-proven window
        shape for the XLA rec program (a single [k, shard_size] batch
        would blow HBM: the bitplane formulation materializes ~25x the
        input in intermediates). Parallel feed: the reader pool splits
        each batch's survivor-row reads across threads (ec/feed.py)."""
        return list(src.batches(BATCH_W, pad_final=True))

    # --- stage N volumes (healthy link: nothing has compiled yet).
    # A reader thread keeps one volume of host batches ahead, so disk
    # reads overlap device staging (pread + device transfer both release
    # the GIL); the steady per-volume cost is max(read, stage), as in
    # the production pipeline's reader/stager split.
    # Budget discipline (round 10): N scales down on a tight budget,
    # each staged volume checkpoints IMMEDIATELY, and staging stops
    # early (keeping >= 2 volumes) if a degraded tunnel burns the
    # clock — BENCH_r05's 650s timeout died inside this loop with
    # nothing checkpointed at all. ---
    import queue as queue_mod
    import threading

    # 6 x 1.12GB staged concurrently fits a v5e's HBM
    N_BATCHED = 6 if left() > 300 else 3
    _warm_stage((10, BATCH_W))
    read_q: "queue_mod.Queue" = queue_mod.Queue(maxsize=2)
    read_meter = {"s": 0.0}
    stop_reading = threading.Event()

    def reader_main() -> None:
        for _ in range(N_BATCHED):
            if stop_reading.is_set():
                break
            tr = time.perf_counter()
            hb = read_batches()
            read_meter["s"] += time.perf_counter() - tr
            read_q.put(hb)
        read_q.put(None)

    t0 = time.perf_counter()
    threading.Thread(target=reader_main, daemon=True).start()
    staged_vols = []
    while True:
        host_batches = read_q.get()
        if host_batches is None:
            break
        sv = []
        for b in host_batches:
            h = coder.stage_async(b)
            block = getattr(h, "block_until_ready", None)
            if block is not None:
                block()
            sv.append(h)
        staged_vols.append(sv)
        out["ledger"] = {
            "n_volumes_staged": len(staged_vols),
            "read_s": round(read_meter["s"], 2),
            "stage_all_s": round(time.perf_counter() - t0, 2),
        }
        ckpt()
        if len(staged_vols) >= 2 and left() < 150:
            # a degraded tunnel is eating the budget: stop staging and
            # measure with what we have (the numbers matter more than N)
            stop_reading.set()
            out.setdefault("skipped", []).append(
                f"staging volumes {len(staged_vols) + 1}..{N_BATCHED} "
                "(budget)")
    n_staged = len(staged_vols)
    stage_all_s = time.perf_counter() - t0
    stage_per_volume_s = stage_all_s / max(n_staged, 1)
    out["ledger"] = {
        "n_volumes_staged": n_staged,
        "read_s": round(read_meter["s"], 2),
        "stage_all_s": round(stage_all_s, 2),
        "stage_per_volume_s": round(stage_per_volume_s, 3),
        "stage_gbps": round(
            n_staged * 10 * shard_size / stage_all_s / 1e9, 2),
    }
    src.close()
    ckpt()

    # --- AOT-warm the rec window program, checkpointed as its own step:
    # the dynamic-matrix window executable is the SAME program
    # phase_encode compiled into the shared persistent cache, so this is
    # normally a disk-cache hit measured in seconds — and when it ISN'T
    # (cold cache, wedge-prone remote compile), the phase dies in a step
    # whose absence from the partial record names the culprit ---
    try:
        t0 = time.perf_counter()
        coder.warm_rec_digest_window(survivors, tuple(VICTIMS),
                                     len(staged_vols[0]), (10, BATCH_W))
        out["rec_warm_s"] = round(time.perf_counter() - t0, 2)
    except Exception as e:  # advisory: dispatch compiles lazily instead
        out["rec_warm_error"] = str(e)[:300]
    ckpt()

    # --- first dispatch: one window through the SHARED dynamic-matrix
    # program (compile hits the persistent cache the encode phase
    # already populated; a cold compile here is the wedge-prone step,
    # which is why everything above is already checkpointed) ---
    t0 = time.perf_counter()
    acc0 = coder.rec_digest_window_async(survivors, tuple(VICTIMS),
                                         staged_vols[0])
    acc0.block_until_ready()
    cold_exec_s = time.perf_counter() - t0
    out["cold_pass_s"] = round(stage_per_volume_s + cold_exec_s, 2)
    out["cold_exec_s"] = round(cold_exec_s, 2)
    ckpt()

    # --- steady: remaining volumes through the loaded program,
    # dispatches pipelined, one block at the end ---
    accs = [acc0]
    t0 = time.perf_counter()
    for sv in staged_vols[1:]:
        accs.append(coder.rec_digest_window_async(
            survivors, tuple(VICTIMS), sv))
    accs[-1].block_until_ready()  # TPU executes in dispatch order
    exec_s = ((time.perf_counter() - t0) / (n_staged - 1)
              if n_staged > 1 else cold_exec_s)
    out["exec_steady_s"] = round(exec_s, 4)

    p50 = stage_per_volume_s + exec_s
    out["rebuild_p50_s"] = round(p50, 3)
    out["rebuild_is_cold"] = False
    # rate over the data the rebuild actually moves + computes: k
    # survivor shards in, len(victims) shards out
    out["rebuild_gbps"] = round(10 * shard_size / p50 / 1e9, 2)
    # chip-side reconstruction rate (window executable, pipelined)
    out["rebuild_window_gbps"] = round(10 * shard_size / exec_s / 1e9, 2)
    ckpt()

    # extra pipelined reps on volume 0's staged window (acc-chained);
    # optional: skipped on a tight budget so the verify still runs
    R = 5
    acc_r = None
    if left() > 90:
        t0 = time.perf_counter()
        for _ in range(R):
            acc_r = coder.rec_digest_window_async(
                survivors, tuple(VICTIMS), staged_vols[0], acc_r)
        acc_r.block_until_ready()
        exec_rep_s = (time.perf_counter() - t0) / R
        out["exec_steady_rep_s"] = round(exec_rep_s, 4)
        ckpt()
    else:
        out["exec_steady_rep_s"] = None
        out["skipped"] = ["exec_steady_rep (budget)"]

    # --- first D2H: materialize + verify everything ---
    for a in accs:
        d = np.asarray(coder.materialize(a), dtype=np.uint32)
        if d.tolist() != want.tolist():
            raise AssertionError(f"rebuild digest {d} != files {want}")
    if acc_r is not None:
        d_r = np.asarray(coder.materialize(acc_r), dtype=np.uint32)
        want_r = (want.astype(np.uint64) * R & 0xFFFFFFFF).astype(np.uint32)
        if d_r.tolist() != want_r.tolist():
            raise AssertionError("pipelined rebuild digest mismatch")
    out["digest_verified"] = True
    ckpt()
    if left() > 30:
        t0 = time.perf_counter()
        acc1 = coder.rec_digest_window_async(survivors, tuple(VICTIMS),
                                             staged_vols[0])
        d1 = np.asarray(coder.materialize(acc1), dtype=np.uint32)
        out["single_rep_sync_s"] = round(time.perf_counter() - t0, 4)
        if d1.tolist() != want.tolist():
            raise AssertionError("steady-state rebuild digest mismatch")
    else:
        out["single_rep_sync_s"] = None
        out.setdefault("skipped", []).append("single_rep_sync (budget)")

    # --- BASELINE config 3 batch summary + amortization curve ---
    load_s = max(cold_exec_s - exec_s, 0.0)
    batch = {
        str(n_staged): {
            "wall_s": round(stage_all_s + cold_exec_s
                            + exec_s * (n_staged - 1), 2),
            "per_volume_s": round(p50 + load_s / n_staged, 3),
            "gbps_aggregate": round(
                10 * shard_size * n_staged
                / (stage_all_s + cold_exec_s + exec_s * (n_staged - 1))
                / 1e9, 2),
        },
        "amortization_model": {
            "one_time_load_s": round(load_s, 1),
            "steady_per_volume_s": round(p50, 3),
            "projected_per_volume_s": {
                str(n): round((load_s + n * p50) / n, 2)
                for n in (1, 10, 100, 1000)},
        },
    }
    out["rebuild_batch"] = batch
    ckpt()
    return out


def bench_kernel(k: int, m: int, n: int, reps: int, tile=None, rounds=1,
                 method=None):
    """Pinned kernel measurement (unchanged from round 3): fixed n and
    reps, one warm+correctness pass, `rounds` timed rounds; returns
    (median GB/s, spread). `method` selects the GF formulation
    (rs_jax.FORMULATIONS; on TPU the Pallas twin where one exists) —
    None keeps the historical default so pinned-anchor numbers stay
    comparable across bench rounds."""
    import jax

    from seaweedfs_tpu.ops import gf256, rs_jax, rs_pallas

    data = jax.numpy.asarray(
        np.random.default_rng(0).integers(0, 256, (k, n), dtype=np.uint8))
    if jax.default_backend() == "tpu":
        if method in (None, "bitplane", "xorsched"):
            fn = rs_pallas.gf_apply_pallas(
                gf256.parity_matrix(k, m),
                tile=tile or rs_pallas.DEFAULT_TILE,
                formulation=method or "bitplane")
        else:  # lut has no Pallas twin: measure the XLA program
            fn = jax.jit(rs_jax.gf_apply(method,
                                         gf256.parity_matrix(k, m)))
    elif method is None:
        fn = jax.jit(rs_jax.gf_apply_bitplane(gf256.parity_matrix(k, m)))
    else:
        fn = jax.jit(rs_jax.gf_apply(method, gf256.parity_matrix(k, m)))
    out = fn(data)
    out.block_until_ready()

    check = np.asarray(out[:, :65536])
    want = gf256.encode_parity(np.asarray(data[:, :65536]), m)
    if not np.array_equal(check, want):
        raise AssertionError(f"parity mismatch at RS({k},{m})")

    # single-launch wall (dispatch + block): when the tunnel stops
    # pipelining launches, the timed loop degenerates to reps x this
    # latency and the GB/s figure measures the tunnel, not the chip.
    # Only the pinned multi-round call pays for it — sweep calls
    # (rounds=1) discard it, and on a latency-bound tunnel the extra
    # launch would cost seconds each
    single_launch_s = 0.0
    if rounds > 1:
        t0 = time.perf_counter()
        out = fn(data)
        out.block_until_ready()
        single_launch_s = time.perf_counter() - t0

    samples = []
    for _ in range(rounds):
        t0 = time.perf_counter()
        for _ in range(reps):
            out = fn(data)
        out.block_until_ready()
        samples.append((k * n) / ((time.perf_counter() - t0) / reps) / 1e9)
    med = statistics.median(samples)
    spread = (max(samples) - min(samples)) / med if med else 0.0
    return med, spread, single_launch_s


def phase_kernel(work: str = "", budget_s: float = 390.0) -> dict:
    """Pinned kernel + RS(k,m) sweep (config 4) + tile sweep, ordered so
    every config reports at least one number before optional extras.

    Every sweep/tile cell is pre-filled with a "skipped: not reached"
    reason and the record checkpoints after each cell, so a phase that
    times out mid-sweep leaves reason strings in <work>/kernel_partial
    .json instead of nulling cells it never got to (BENCH_r05 recorded
    a bare null at tile 131072 exactly this way)."""
    import jax

    from seaweedfs_tpu.ops import rs_pallas

    on_tpu = jax.default_backend() == "tpu"
    n = 64 * MB if on_tpu else MB
    reps = 10 if on_tpu else 3
    started = time.perf_counter()
    out: dict = {"backend": jax.default_backend()}

    def ckpt() -> None:
        if work:
            _phase_checkpoint(work, "kernel", out)

    def left() -> float:
        return budget_s - (time.perf_counter() - started)

    # 1) pinned anchor first: every config must report a number before
    # anything optional spends budget (round 4 nulled (6,3) + the tile
    # sweep). Full reps + 3 rounds — at reps=3 the unamortized launch
    # latency halves the reported rate (measured round 5: 14.98 vs 33+);
    # the timed loop itself costs <2s, compiles dominate each config.
    t0 = time.perf_counter()
    gbps, spread, single_s = bench_kernel(10, 4, n, reps, rounds=3)
    per_rep_s = (10 * n) / (gbps * 1e9) if gbps else 0.0
    launch_bound = single_s > 0.05 and per_rep_s > 0.7 * single_s
    out["kernel"] = {
        "gbps": round(gbps, 2),
        "vs_target": round(gbps / BASELINE_GBPS, 3),
        "n": n, "reps": reps, "rounds": 3,
        "spread_pct": round(spread * 100, 1),
        "single_launch_s": round(single_s, 3),
        "launch_latency_bound": launch_bound,
    }
    if launch_bound:
        out["kernel"]["caveat"] = (
            "this run's timed loop degenerated to per-launch tunnel "
            f"latency ({single_s:.2f}s/launch, no pipelining): the "
            "GB/s figure measures the tunnel, not the kernel; "
            "healthy-session measurements of the same pinned config "
            "are 33-37 GB/s")
    last = max(45.0, time.perf_counter() - t0)
    ckpt()

    # 2) geometry sweep — every cell before any optional extra. A cell
    # that can't run records WHY as a string ("skipped: ..."/"error: ...")
    # instead of a bare null, and the dicts start fully populated with
    # "skipped: not reached" so even a phase KILLED mid-cell leaves a
    # reason string, never a null (BENCH_r05 recorded "131072": null —
    # the per-cell strings existed but only materialized for cells the
    # loop actually visited before the phase timed out).
    not_reached = "skipped: not reached (phase timed out or died earlier)"
    sweep: dict = {f"{k},{m}": not_reached
                   for (k, m) in ((20, 4), (12, 4), (6, 3))}
    tiles: dict = {tl: not_reached
                   for tl in dict.fromkeys(
                       (rs_pallas.DEFAULT_TILE, 65536, 131072))}
    forms: dict = {f"{f}:{k},{m}": not_reached
                   for f in ("lut", "bitplane", "xorsched")
                   for (k, m) in ((10, 4), (12, 4), (20, 4))}
    out["sweep_kernel_gbps"] = sweep
    out["tile_sweep_gbps"] = tiles
    out["formulation_sweep_gbps"] = forms
    ckpt()

    # 2a) static formulation metric: compiled-HLO element-ops per input
    # byte for each formulation's RS(10,4) encode program (xorsched's is
    # the packed bit-plane-resident per-batch program — the one the
    # windowed path actually launches). Cheap (lower+compile, no timed
    # loop) and meaningful without a TPU, so it lands before the sweeps.
    from seaweedfs_tpu.ops import rs_jax as _rs_jax
    hlo: dict = {}
    out["hlo_ops_per_byte"] = hlo
    for f in ("lut", "bitplane", "xorsched"):
        try:
            hlo[f] = round(
                _rs_jax.encode_hlo_ops_per_byte(10, 4, method=f), 2)
        except Exception as e:
            hlo[f] = f"error: {type(e).__name__}: {str(e)[:160]}"
        ckpt()
    for (k, m) in ((20, 4), (12, 4), (6, 3)):
        if left() < last * 1.2:
            sweep[f"{k},{m}"] = (f"skipped: budget ({left():.0f}s left, "
                                 f"cell needs ~{last * 1.2:.0f}s)")
            ckpt()
            continue
        t0 = time.perf_counter()
        nn = n - n % (16384 * 8)
        try:
            g, _, _ = bench_kernel(k, m, nn, reps)
        except Exception as e:
            sweep[f"{k},{m}"] = (f"error: {type(e).__name__}: "
                                 f"{str(e)[:160]}")
            last = max(45.0, time.perf_counter() - t0)
            ckpt()
            continue
        last = max(45.0, time.perf_counter() - t0)
        sweep[f"{k},{m}"] = round(g, 2)
        ckpt()

    # 3) tile sweep (DEFAULT_TILE reuses the step-1 compile)
    for tl in list(tiles):
        if left() < last * 1.2:
            tiles[tl] = (f"skipped: budget ({left():.0f}s left, "
                         f"cell needs ~{last * 1.2:.0f}s)")
            ckpt()
            continue
        t0 = time.perf_counter()
        try:
            g, _, _ = bench_kernel(10, 4, n, reps, tile=tl)
        except Exception as e:
            tiles[tl] = f"error: {type(e).__name__}: {str(e)[:160]}"
            last = max(45.0, time.perf_counter() - t0)
            ckpt()
            continue
        last = max(45.0, time.perf_counter() - t0)
        tiles[tl] = round(g, 2)
        ckpt()

    # 4) formulation sweep: {lut, bitplane, xorsched} x geometry. On CPU
    # hosts this times the XLA programs (relative ordering only); the
    # TPU round times the Pallas twins where they exist. Same budget
    # convention as the other sweeps: every unvisited cell keeps a
    # reason string, never a null.
    for key in list(forms):
        f, geo = key.split(":")
        k, m = (int(x) for x in geo.split(","))
        if left() < last * 1.2:
            forms[key] = (f"skipped: budget ({left():.0f}s left, "
                          f"cell needs ~{last * 1.2:.0f}s)")
            ckpt()
            continue
        t0 = time.perf_counter()
        nn = n - n % (16384 * 8)
        try:
            g, _, _ = bench_kernel(k, m, nn, reps, method=f)
        except Exception as e:
            forms[key] = (f"error: {type(e).__name__}: "
                          f"{str(e)[:160]}")
            last = max(45.0, time.perf_counter() - t0)
            ckpt()
            continue
        last = max(45.0, time.perf_counter() - t0)
        forms[key] = round(g, 2)
        ckpt()

    # arithmetic context for the kernel number
    ops_per_s = 128 * 4 * out["kernel"]["gbps"] * 1e9
    out["kernel"]["mxu_fraction"] = round(ops_per_s / 394e12, 4)
    out["kernel"]["hbm_fraction"] = round(1.4 * out["kernel"]["gbps"] / 819,
                                          4)
    out["kernel"]["bound"] = (
        "VPU (bitplane expand/repack): ~18 int32 VPU ops/input byte puts "
        "that formulation's ceiling near 52 GB/s on v5e; an MXU-repack "
        "variant measured SLOWER (32.4 vs 35.4 GB/s — M=4 rows occupy "
        "~3% of the systolic array; see ops/rs_pallas.py). Wider "
        "geometries amortize the expand: RS(20,4) exceeds 60 GB/s. The "
        "xorsched formulation (ops/xor_schedule.py) removes the bound's "
        "cause instead of amortizing it: a CSE'd XOR schedule over "
        "uint32-packed bit-plane words cuts RS(10,4) to ~2.3 compiled "
        "element-ops/input byte (hlo_ops_per_byte; schedule 499 XORs vs "
        "the 1192 dense popcount bound) with zero expansion traffic "
        "when batches stay bit-plane-resident across the window "
        "(ec/coder.py stage-time pack) — its ceiling is HBM streaming, "
        "not the VPU; chip-side GB/s lands at the next TPU-host round.")
    return out


def phase_fused(work: str, budget_s: float = 580.0) -> dict:
    """Config 5: the one-pass warm-down (ec/fused.py) against the
    chained vacuum -> gzip -> encode -> scrub-digest path it replaces,
    over the same mixed volume (half compressible, half not — real
    volumes are a mix; round 3's all-text volume measured gzip only).

    `gbps` is the fused steady rate (commit fsyncs excluded — they
    overlap the NEXT volume in the lifecycle batcher's window),
    `gbps_durable` includes them, `speedup` is fused steady over the
    chained wall. `phase_s` breaks the pass down by governor stage
    (ec.compact / ec.gzip / ec.read / ec.kernel / ec.write / ec.digest)
    from the same observe spans the feed governor retunes on.
    `scrub_redigests` proves the scrubber's first verification rode the
    pass: stamp_shard_digests finds nothing left to recompute. Each
    step checkpoints via _phase_checkpoint so a budget kill keeps every
    number already measured; late steps self-skip when the budget runs
    low."""
    import jax

    from seaweedfs_tpu import observe
    from seaweedfs_tpu.ec import pipeline, striping
    from seaweedfs_tpu.ec.fused import fused_vacuum_gzip_encode
    from seaweedfs_tpu.ec.geometry import DEFAULT as GEO, to_ext
    from seaweedfs_tpu.storage import idx as idx_mod
    from seaweedfs_tpu.storage import types as st
    from seaweedfs_tpu.storage.needle import FLAG_IS_COMPRESSED, Needle
    from seaweedfs_tpu.storage.superblock import SuperBlock
    from seaweedfs_tpu.storage.volume import Volume
    from seaweedfs_tpu.utils import compression
    from seaweedfs_tpu.utils import metrics as metrics_mod

    t_phase0 = time.perf_counter()

    def left() -> float:
        return budget_s - (time.perf_counter() - t_phase0)

    out: dict = {"backend": jax.default_backend()}
    vdir = os.path.join(work, "fusedvol")
    os.makedirs(vdir, exist_ok=True)
    v = Volume(vdir, "", 7, create=True)
    rng = np.random.default_rng(11)
    text = (b"fused bench payload: compressible text block. " * 5700)
    count = 0
    target = 192 * MB
    written = 0
    while written < target:
        count += 1
        if count % 2:
            data = text[:256 * 1024]
        else:
            data = rng.integers(0, 256, 256 * 1024,
                                dtype=np.uint8).tobytes()
        v.write_needle(Needle(cookie=count, id=count, data=data))
        written += len(data)
    # delete half of EACH kind (ids 1,2 mod 4): the survivors stay a
    # text/random mix — deleting every other id would remove exactly the
    # text needles (odd ids) and leave an all-random volume
    for i in range(1, count + 1):
        if i % 4 in (1, 2):
            v.delete_needle(Needle(cookie=i, id=i))
    src_bytes = v.data_file_size()
    out["src_bytes"] = src_bytes
    _phase_checkpoint(work, "fused", out)

    host = _host_coder()

    # step 1: the one-pass warm-down, under its own trace so the stage
    # breakdown below aggregates exactly this run's governor spans
    dst = os.path.join(vdir, "out_7")
    tctx = observe.TraceCtx(observe.new_id(), "", "bench", "")
    res = observe.run_with(tctx, fused_vacuum_gzip_encode, v, dst, host,
                           batch_size=4 * MB)
    wall_s = res["wall_s"]
    commit_s = res["commit_s"]
    steady_s = max(wall_s - commit_s, 1e-3)
    out.update({
        "compacted_bytes": res["compacted_bytes"],
        "live_needles": res["live_needles"],
        "gzipped_needles": res["gzipped_needles"],
        "gzip_workers": res["gzip_workers"],
        "gbps": round(src_bytes / steady_s / 1e9, 3),
        "gbps_durable": round(src_bytes / wall_s / 1e9, 3),
        "fused_wall_s": round(wall_s, 3),
        "fused_commit_s": round(commit_s, 3),
    })
    totals = observe.stage_totals(tctx.trace_id, prefix="ec.")
    out["phase_s"] = {name[3:]: round(us / 1e6, 3)
                      for name, (_, us) in sorted(totals.items())}
    stages = {k: v for k, v in out["phase_s"].items()
              if k in ("compact", "gzip", "read", "dispatch",
                       "kernel", "write", "digest")}
    if stages:
        out["bottleneck"] = max(stages, key=stages.get)
    _phase_checkpoint(work, "fused", out)

    # step 2: scrubber rides the pass — stamp_shard_digests (the mount/
    # scrub path's backfill) must find every digest already in the .ecm,
    # and the stamped values must match a fresh host digest of the bytes
    reg = metrics_mod.shared("ec")
    before = reg.value("ec_digest_host_recompute")
    pipeline.stamp_shard_digests(dst, GEO)
    out["scrub_redigests"] = int(
        reg.value("ec_digest_host_recompute") - before)
    stamped = pipeline.read_stamped_digests(dst)
    shard_ids = list(range(GEO.total_shards))
    true_dig = pipeline.shard_file_digest(dst, shard_ids)
    for sid in shard_ids:
        if stamped.get(sid) != int(true_dig[sid]):
            raise AssertionError(
                f"fused stamped digest wrong for shard {sid}")
    _phase_checkpoint(work, "fused", out)

    # step 3: the chained baseline it replaces — per-needle compact +
    # gzip into dst, then stream_encode, sorted .ecx, and the host
    # re-digest the scrubber's first verification used to cost
    if left() < 45.0:
        out["baseline"] = {"error": "skipped (budget)"}
        v.close()
        _phase_checkpoint(work, "fused", out)
        return out
    seq = os.path.join(vdir, "seq_7")
    t0 = time.perf_counter()
    with v._lock:
        snapshot = [nv for nv in v.nm.values()
                    if st.size_is_valid(nv.size)]
        sb = SuperBlock(
            version=v.super_block.version,
            replica_placement=v.super_block.replica_placement,
            ttl=v.super_block.ttl,
            compaction_revision=v.super_block.compaction_revision + 1,
            extra=v.super_block.extra)
    snapshot.sort(key=lambda nv: nv.offset)
    with open(seq + ".dat", "wb") as dat, open(seq + ".idx", "wb") as ix:
        dat.write(sb.to_bytes())
        offset = len(sb.to_bytes())
        for nv in snapshot:
            n = v.read_needle_at(st.stored_to_offset(nv.offset), nv.size)
            if n.data and not n.is_compressed \
                    and v.version != st.VERSION1:
                head = n.data[:4096]
                trial = compression.compress(head, level=1)
                if len(trial) * 10 < len(head) * 9:
                    comp = compression.compress(n.data, level=1)
                    if len(comp) * 10 < len(n.data) * 9:
                        n.data = comp
                        n.set_flag(FLAG_IS_COMPRESSED)
            record = n.to_bytes(v.version)
            if offset % st.NEEDLE_PADDING_SIZE:
                pad = (-offset) % st.NEEDLE_PADDING_SIZE
                dat.write(bytes(pad))
                offset += pad
            dat.write(record)
            ix.write(idx_mod.pack_entry(
                nv.key, st.offset_to_stored(offset, v.offset_size),
                n.size, offset_size=v.offset_size))
            offset += len(record)
    t_compact_gzip = time.perf_counter() - t0
    v.close()
    t0 = time.perf_counter()
    pipeline.stream_encode(seq, host, batch_size=4 * MB)
    striping.write_sorted_ecx_from_idx(seq, offset_size=v.offset_size)
    t_encode = time.perf_counter() - t0
    t0 = time.perf_counter()
    pipeline.shard_file_digest(seq, shard_ids)  # scrubber's first verify
    t_scrub = time.perf_counter() - t0
    baseline_wall = t_compact_gzip + t_encode + t_scrub
    out["baseline"] = {
        "compact_gzip_s": round(t_compact_gzip, 3),
        "encode_s": round(t_encode, 3),
        "scrub_digest_s": round(t_scrub, 3),
        "wall_s": round(baseline_wall, 3),
        "gbps": round(src_bytes / baseline_wall / 1e9, 3),
    }
    out["speedup"] = round(
        out["gbps"] / max(out["baseline"]["gbps"], 1e-9), 2)
    _phase_checkpoint(work, "fused", out)

    # step 4: identity spot check — same compacted bytes, same shards
    for ext in (".dat", ".ecx", to_ext(0), to_ext(GEO.total_shards - 1)):
        with open(seq + ext, "rb") as a, open(dst + ext, "rb") as b:
            if a.read() != b.read():
                raise AssertionError(
                    f"fused output diverges from chained path at {ext}")
    out["identical_to_chained"] = True
    _phase_checkpoint(work, "fused", out)
    return out


def bench_system(work: str, n: int = 6000, size: int = 1024,
                 concurrency: int = 16) -> dict:
    """System req/s vs the reference's published benchmark
    (README.md:504-553: 15,708 writes/s, 47,019 reads/s at 1KB, c=16 on
    a multi-core 2014 MacBook i7 running BOTH the Go server and the Go
    client). Here the combined server + the raw-socket self-validating
    client share this host; workers scale with available cores."""
    import urllib.request

    from seaweedfs_tpu.utils.bench_client import run_benchmark

    import seaweedfs_tpu
    pkg_root = os.path.dirname(os.path.dirname(seaweedfs_tpu.__file__))
    env = dict(os.environ, JAX_PLATFORMS="cpu", SEAWEEDFS_FORCE_CPU="1")
    env["PYTHONPATH"] = pkg_root + os.pathsep + env.get("PYTHONPATH", "")

    def _one(workers: int, tag: str) -> dict:
        mport, vport = 19555, 18555
        data_dir = os.path.join(work, f"sysbench_{tag}")
        os.makedirs(data_dir, exist_ok=True)
        proc = subprocess.Popen(
            [sys.executable, "-m", "seaweedfs_tpu.cli", "server",
             "-ip", "127.0.0.1", "-master_port", str(mport),
             "-port", str(vport), "-dir", data_dir,
             "-volume_workers", str(workers)],
            cwd=data_dir, env=env,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
        try:
            deadline = time.time() + 30
            while True:
                try:
                    with urllib.request.urlopen(
                            f"http://127.0.0.1:{mport}/dir/assign",
                            timeout=2) as r:
                        if "fid" in json.loads(r.read()):
                            break
                except Exception:
                    pass
                if time.time() > deadline:
                    raise RuntimeError("combined server failed to start")
                time.sleep(0.3)
            # warm pass (discarded): volume growth, page allocation and
            # connection setup otherwise land in the first timed batch
            run_benchmark(f"127.0.0.1:{mport}", n=400, size=size,
                          concurrency=concurrency)
            return run_benchmark(f"127.0.0.1:{mport}", n=n, size=size,
                                 concurrency=concurrency)
        finally:
            proc.terminate()
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
            time.sleep(0.5)  # let the ports free before the next boot

    workers = max(1, min(4, (os.cpu_count() or 1) - 1)) \
        if (os.cpu_count() or 1) > 1 else 1
    out = _one(workers, "w1")
    # worker-scaling row (round-4 verdict: prove or drop the per-core
    # parity claim). On a 1-core host a flat/negative slope IS the
    # measured ceiling evidence: the binding resource is the shared
    # core, not the worker count.
    try:
        w2 = _one(workers + 1, "w2")
        out["scaling"] = {
            "volume_workers": workers + 1,
            "write_req_s": w2["write"]["req_s"],
            "read_req_s": w2["read"]["req_s"],
            "write_slope_vs_base": round(
                w2["write"]["req_s"] / max(out["write"]["req_s"], 1), 3),
            "read_slope_vs_base": round(
                w2["read"]["req_s"] / max(out["read"]["req_s"], 1), 3),
            "note": ("server+client share os.cpu_count() core(s); a "
                     "slope <= 1.0 on a 1-core host means the shared "
                     "core, not the worker count, is the ceiling "
                     "(extra workers only add context switching there; "
                     "on multi-core hosts each worker is a "
                     "share-nothing process on its own core)"),
        }
    except Exception as e:
        out["scaling"] = {"error": str(e)}

    def _one_sharded(shards: int) -> dict:
        # the share-nothing SO_REUSEPORT fleet (server/sharded.py): the
        # combined `server` command doesn't fork shards, so this boots
        # the phase_saturation shape — master + WEED_SERVE_SHARDS=N
        # volume — on this phase's ports
        mport, vport = 19555, 18555
        base = os.path.join(work, f"sysbench_sh{shards}")
        mdir, vdir = os.path.join(base, "m"), os.path.join(base, "v")
        os.makedirs(mdir, exist_ok=True)
        os.makedirs(vdir, exist_ok=True)
        senv = dict(env, WEED_SERVE_SHARDS=str(shards))
        procs = [subprocess.Popen(
            [sys.executable, "-m", "seaweedfs_tpu.cli", "master",
             "-port", str(mport), "-mdir", mdir, "-grpc_port", "0",
             "-pulse", "1"], env=senv,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)]
        try:
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "seaweedfs_tpu.cli", "volume",
                 "-port", str(vport), "-dir", vdir,
                 "-mserver", f"127.0.0.1:{mport}", "-grpc_port", "0",
                 "-pulse", "1"], env=senv,
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL))
            deadline = time.time() + 60
            while True:
                try:
                    with urllib.request.urlopen(
                            f"http://127.0.0.1:{mport}/dir/assign",
                            timeout=2) as r:
                        if "fid" in json.loads(r.read()):
                            break
                except Exception:
                    pass
                if time.time() > deadline:
                    raise RuntimeError(
                        f"shards={shards} fleet failed to start")
                time.sleep(0.3)
            time.sleep(1.0)  # first stripe tick publishes shard routes
            run_benchmark(f"127.0.0.1:{mport}", n=400, size=size,
                          concurrency=concurrency)
            return run_benchmark(f"127.0.0.1:{mport}", n=n, size=size,
                                 concurrency=concurrency)
        finally:
            for p in reversed(procs):
                p.terminate()
            for p in procs:
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    p.kill()
            time.sleep(0.5)

    # multi-core re-baseline row: the single-process numbers above stand
    # next to the sharded fleet's, so the next bench round on a
    # multi-core host re-anchors the serving baseline without a code
    # change; a 1-core host records WHY there's no row instead of a null
    cores = os.cpu_count() or 1
    if cores > 1:
        shards = max(2, min(4, cores))
        try:
            sh = _one_sharded(shards)
            out["sharded"] = {
                "shards": shards,
                "write_req_s": sh["write"]["req_s"],
                "read_req_s": sh["read"]["req_s"],
                "write_slope_vs_single": round(
                    sh["write"]["req_s"] / max(out["write"]["req_s"], 1),
                    3),
                "read_slope_vs_single": round(
                    sh["read"]["req_s"] / max(out["read"]["req_s"], 1),
                    3),
            }
        except Exception as e:
            out["sharded"] = {"error": f"{type(e).__name__}: "
                                       f"{str(e)[:160]}"}
    else:
        out["sharded"] = ("skipped: 1-core host (the fleet only adds "
                          "context switching; boots when "
                          "os.cpu_count() > 1)")
    out["cpu_count"] = os.cpu_count()
    out["volume_workers"] = workers
    out["vs_reference"] = {
        "ref_write_req_s": 15708, "ref_read_req_s": 47019,
        "write_ratio": round(out["write"]["req_s"] / 15708, 4),
        "read_ratio": round(out["read"]["req_s"] / 47019, 4),
        "note": ("reference ran server+client on a multi-core i7; this "
                 "host pins both to os.cpu_count() core(s). Per-core "
                 "(ref assumed 4 cores): write "
                 f"{round(out['write']['req_s'] / max((os.cpu_count() or 1), 1) / (15708 / 4), 2)}x, "
                 "read "
                 f"{round(out['read']['req_s'] / max((os.cpu_count() or 1), 1) / (47019 / 4), 2)}x"),
    }
    return out


def phase_saturation(work: str, budget_s: float = 240.0,
                     n: int = 2500, size: int = 1024,
                     concurrency: int = 16) -> dict:
    """Share-nothing shard-fleet saturation: boots a master plus a
    WEED_SERVE_SHARDS=N volume server (the SO_REUSEPORT fleet forked
    by the CLI) and runs the same 1KB write/read benchmark once at
    shards=1 (the single-process path) and once at shards=min(4,
    host cores, 2 minimum). Acceptance on multi-core hosts is
    saturation throughput >= 2.5x the single-shard run; on a 1-core
    host the fleet only adds context switching, so host_cores is
    recorded and the slope stands as measured-ceiling evidence
    (same verdict idiom as bench_system's worker-scaling row)."""
    import urllib.request

    from seaweedfs_tpu.utils.bench_client import run_benchmark

    import seaweedfs_tpu
    pkg_root = os.path.dirname(os.path.dirname(seaweedfs_tpu.__file__))
    cores = os.cpu_count() or 1
    fleet = max(2, min(4, cores))
    deadline = time.time() + budget_s

    def _one(shards: int, tag: str) -> dict:
        mport, vport = 19666, 18666
        base = os.path.join(work, f"sat_{tag}")
        mdir, vdir = os.path.join(base, "m"), os.path.join(base, "v")
        os.makedirs(mdir, exist_ok=True)
        os.makedirs(vdir, exist_ok=True)
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   SEAWEEDFS_FORCE_CPU="1",
                   WEED_SERVE_SHARDS=str(shards))
        env["PYTHONPATH"] = pkg_root + os.pathsep + env.get(
            "PYTHONPATH", "")
        procs = [subprocess.Popen(
            [sys.executable, "-m", "seaweedfs_tpu.cli", "master",
             "-port", str(mport), "-mdir", mdir, "-grpc_port", "0",
             "-pulse", "1"], env=env,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)]
        try:
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "seaweedfs_tpu.cli", "volume",
                 "-port", str(vport), "-dir", vdir,
                 "-mserver", f"127.0.0.1:{mport}", "-grpc_port", "0",
                 "-pulse", "1"], env=env,
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL))
            boot_deadline = time.time() + 60
            while True:
                try:
                    with urllib.request.urlopen(
                            f"http://127.0.0.1:{mport}/dir/assign",
                            timeout=2) as r:
                        if "fid" in json.loads(r.read()):
                            break
                except Exception:
                    pass
                if time.time() > boot_deadline:
                    raise RuntimeError(
                        f"shards={shards} fleet failed to start")
                time.sleep(0.3)
            time.sleep(1.0)  # first stripe tick publishes shard routes
            # warm pass (discarded): volume growth + route discovery
            run_benchmark(f"127.0.0.1:{mport}", n=min(300, n),
                          size=size, concurrency=concurrency)
            out = run_benchmark(f"127.0.0.1:{mport}", n=n, size=size,
                                concurrency=concurrency)
            out["shards"] = shards
            return out
        finally:
            for p in reversed(procs):
                p.terminate()
            for p in procs:
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    p.kill()
            time.sleep(0.5)  # let the reuseport group free the port

    single = _one(1, "s1")
    out: dict = {
        "host_cores": cores,
        "shards": fleet,
        "single": {"write_req_s": single["write"]["req_s"],
                   "read_req_s": single["read"]["req_s"]},
    }
    if time.time() > deadline:
        out["fleet"] = {"error": "skipped (budget)"}
        return out
    try:
        multi = _one(fleet, f"s{fleet}")
        out["fleet"] = {"write_req_s": multi["write"]["req_s"],
                        "read_req_s": multi["read"]["req_s"]}
        w_x = round(multi["write"]["req_s"]
                    / max(single["write"]["req_s"], 1), 3)
        r_x = round(multi["read"]["req_s"]
                    / max(single["read"]["req_s"], 1), 3)
        out["speedup"] = {"write": w_x, "read": r_x}
        out["accept"] = {
            "target": "fleet >= 2.5x single (multi-core hosts only)",
            "applies": cores >= fleet,
            "write_2_5x": w_x >= 2.5,
            "read_2_5x": r_x >= 2.5,
            "note": (None if cores >= fleet else
                     f"host has {cores} core(s): {fleet} shards time-"
                     "slice one core, so the slope measures context-"
                     "switch overhead, not per-core scaling"),
        }
    except Exception as e:  # noqa: BLE001 - recorded, not fatal
        out["fleet"] = {"error": str(e)}
    return out


def phase_largefile(work: str, size_mb: int = 64) -> dict:
    """Write-tier number beyond req/s: single-stream large-file filer
    PUT and GET MB/s through the pipelined chunk-upload window + fid
    lease (ISSUE 5). Boots master+volume+filer in one combined-server
    process (8 MB chunks -> size_mb/8 chunks per PUT), uploads one
    large body, reads it back, verifies byte identity. Every measured
    value checkpoints to largefile_partial.json the moment it exists."""
    import hashlib
    import urllib.request

    import seaweedfs_tpu
    pkg_root = os.path.dirname(os.path.dirname(seaweedfs_tpu.__file__))
    env = dict(os.environ, JAX_PLATFORMS="cpu", SEAWEEDFS_FORCE_CPU="1")
    env["PYTHONPATH"] = pkg_root + os.pathsep + env.get("PYTHONPATH", "")

    mport, vport, fport = 19666, 18666, 18999
    data_dir = os.path.join(work, "largefile")
    os.makedirs(data_dir, exist_ok=True)
    out: dict = {"size_mb": size_mb}
    proc = subprocess.Popen(
        [sys.executable, "-m", "seaweedfs_tpu.cli", "server",
         "-ip", "127.0.0.1", "-master_port", str(mport),
         "-port", str(vport), "-dir", data_dir,
         "-filer", "-filer_port", str(fport),
         "-filer_db", os.path.join(data_dir, "filer.db")],
        cwd=data_dir, env=env,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    try:
        deadline = time.time() + 30
        while True:
            try:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{fport}/healthz",
                        timeout=2) as r:
                    if json.load(r).get("ok"):
                        break
            except Exception:
                pass
            if time.time() > deadline:
                raise RuntimeError("combined server failed to start")
            time.sleep(0.3)

        rng = np.random.default_rng(11)
        body = rng.integers(0, 256, size_mb * 1024 * 1024,
                            dtype=np.uint8).tobytes()
        digest = hashlib.md5(body).hexdigest()

        def put(path: str) -> float:
            req = urllib.request.Request(
                f"http://127.0.0.1:{fport}{path}", data=body,
                method="PUT",
                headers={"Content-Type": "application/octet-stream"})
            t0 = time.perf_counter()
            with urllib.request.urlopen(req, timeout=300) as r:
                r.read()
            return time.perf_counter() - t0

        def get(path: str) -> tuple[float, str]:
            t0 = time.perf_counter()
            h = hashlib.md5()
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{fport}{path}", timeout=300) as r:
                while True:
                    block = r.read(1 << 20)
                    if not block:
                        break
                    h.update(block)
            return time.perf_counter() - t0, h.hexdigest()

        put("/bench/warm.bin")  # volume growth + connection warmup
        put_s = put("/bench/large.bin")
        out["put_mb_s"] = round(size_mb / put_s, 1)
        out["put_wall_s"] = round(put_s, 3)
        _phase_checkpoint(work, "largefile", out)
        get_s, got = get("/bench/large.bin")
        out["get_mb_s"] = round(size_mb / get_s, 1)
        out["get_wall_s"] = round(get_s, 3)
        out["verified"] = got == digest
        if not out["verified"]:
            out["error"] = "GET digest mismatch"
        # lease effectiveness during the run, straight from the filer
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{fport}/metrics", timeout=5) as r:
                text = r.read().decode()
            vals = {}
            for line in text.splitlines():
                if line.startswith("seaweedfs_tpu_filer_assign_lease_"):
                    k, _, v = line.partition(" ")
                    vals[k.rsplit("_", 2)[-2]] = float(v)
            h_, m_ = vals.get("hit", 0.0), vals.get("miss", 0.0)
            if h_ + m_:
                out["assign_lease_hit_rate"] = round(h_ / (h_ + m_), 3)
        except Exception:
            pass
        _phase_checkpoint(work, "largefile", out)
        return out
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()
        time.sleep(0.5)


def bench_needle_map(work: str, n: int = 5_000_000) -> dict:
    from seaweedfs_tpu.storage.needle_map import DiskNeedleMap

    rec = np.empty(n, dtype=[("k", ">u8"), ("o", ">u4"), ("s", ">u4")])
    rec["k"] = np.arange(1, n + 1)
    rec["o"] = np.arange(1, n + 1)
    rec["s"] = 1000
    path = os.path.join(work, "nmbench.idx")
    rec.tofile(path)
    del rec
    t0 = time.perf_counter()
    nm = DiskNeedleMap(path)
    cold_s = time.perf_counter() - t0
    nm.close()
    t0 = time.perf_counter()
    nm = DiskNeedleMap(path)
    warm_s = time.perf_counter() - t0
    rng = np.random.default_rng(3)
    keys = rng.integers(1, n + 1, 2000)
    lat = []
    for key in keys:
        t0 = time.perf_counter()
        nm.get(int(key))
        lat.append(time.perf_counter() - t0)
    nm.close()
    lat.sort()
    return {"entries": n, "cold_build_s": round(cold_s, 3),
            "warm_open_s": round(warm_s, 4),
            "lookup_p50_us": round(lat[len(lat) // 2] * 1e6, 1),
            "lookup_p99_us": round(lat[int(len(lat) * 0.99)] * 1e6, 1)}


def phase_degraded(work: str, budget_s: float = 240.0,
                   n_reads: int = 300) -> dict:
    """p50/p99 degraded-read latency with one shard holder faulted —
    the warm-storage tier's brownout regime. A real multi-process
    cluster (master + 4 volume server subprocesses, so the fault
    registry is per NODE) EC-encodes the uploaded volume, then
    ``POST /admin/faults`` makes one holder answer every shard read
    with an injected error: reads served by another holder reconstruct
    the missing intervals from the survivors. Budget-aware and
    checkpointed into degraded_partial.json like the other phases."""
    import random as random_mod
    import socket
    import urllib.request

    started = time.perf_counter()

    def left() -> float:
        return budget_s - (time.perf_counter() - started)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from seaweedfs_tpu.client import Client
    from seaweedfs_tpu.shell.ec_commands import EcCommands

    import seaweedfs_tpu
    pkg_root = os.path.dirname(os.path.dirname(seaweedfs_tpu.__file__))
    env = dict(os.environ, JAX_PLATFORMS="cpu", SEAWEEDFS_FORCE_CPU="1")
    env["PYTHONPATH"] = pkg_root + os.pathsep + env.get("PYTHONPATH", "")

    def free_port() -> int:
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    def spawn(args, tag):
        log = open(os.path.join(work, f"degraded_{tag}.log"), "ab")
        return subprocess.Popen(
            [sys.executable, "-m", "seaweedfs_tpu.cli"] + args,
            cwd=work, env=env, stdout=log, stderr=log)

    procs = []
    out: dict = {}
    try:
        mport = free_port()
        master = f"127.0.0.1:{mport}"
        procs.append(spawn(["master", "-port", str(mport), "-mdir", work],
                           "master"))
        for i in range(4):
            vdir = os.path.join(work, f"degraded_vs{i}")
            os.makedirs(vdir, exist_ok=True)
            procs.append(spawn(["volume", "-port", str(free_port()),
                                "-dir", vdir, "-mserver", master,
                                "-pulse", "1"], f"vs{i}"))
        client = Client(master)
        deadline = time.time() + 45
        nodes_up = 0
        while time.time() < deadline:
            try:
                nodes_up = len(client.dir_status().get("nodes", []))
                if nodes_up >= 4:
                    break
            except Exception:
                pass
            time.sleep(0.3)
        if nodes_up == 0:
            raise RuntimeError("degraded cluster never booted "
                               "(0/4 volume servers after 45s)")

        # setup is budget-governed too: on a slow host, uploads against
        # a half-booted cluster retry forever — without these checks the
        # phase hangs PAST its budget instead of recording an error
        rng = random_mod.Random(5)
        blobs: dict[str, bytes] = {}
        for _ in range(60):
            if left() < budget_s * 0.5:
                raise RuntimeError(
                    f"setup over half budget after {len(blobs)}/60 "
                    f"uploads ({nodes_up}/4 volume servers up)")
            data = bytes(rng.getrandbits(8)
                         for _ in range(rng.randint(4096, 32768)))
            blobs[client.upload(data, collection="deg")] = data
        time.sleep(2.0)  # heartbeat rounds so the master sees the volumes
        vids = sorted({int(f.split(",")[0]) for f in blobs})
        shell = EcCommands(client)  # production RS(10,4) geometry
        for vid in vids:
            if left() < 60:
                raise RuntimeError(
                    f"budget exhausted before encoding volume {vid}")
            shell.encode(vid, "deg", apply=True)
        time.sleep(2.0)

        # a ~1.5MB volume striped at 1MB small blocks puts ALL the data
        # in shards 0-1 — fault the holder of shard 0 (where the bytes
        # live) and read through a holder that has NO data shard
        # locally, so every measured read crosses the wire and shard-0
        # reads reconstruct from survivors
        shards_map = client.ec_lookup(vids[0]).get("shards", {})
        holder_urls = sorted({u for urls in shards_map.values()
                              for u in urls})
        assert len(holder_urls) >= 2, holder_urls
        data_holders = {u for sid in ("0", "1")
                        for u in shards_map.get(sid, [])}
        victim = shards_map["0"][0]
        non_data = [u for u in holder_urls if u not in data_holders]
        reader = non_data[0] if non_data else next(
            u for u in holder_urls if u != victim)
        fids = list(blobs)

        def measure(n: int) -> list[float]:
            lat = []
            for i in range(n):
                if left() < 20:
                    break
                fid = fids[i % len(fids)]
                t0 = time.perf_counter()
                with urllib.request.urlopen(
                        f"http://{reader}/{fid}", timeout=30) as r:
                    body = r.read()
                lat.append(time.perf_counter() - t0)
                assert body == blobs[fid], f"corrupt read of {fid}"
            return lat

        def pctl(lat: list[float], q: float) -> float:
            return round(
                sorted(lat)[min(len(lat) - 1, int(len(lat) * q))] * 1e3, 3)

        healthy = measure(min(n_reads, 100))
        out["healthy_p50_ms"] = pctl(healthy, 0.50)
        out["healthy_p99_ms"] = pctl(healthy, 0.99)
        _phase_checkpoint(work, "degraded", out)

        # fault the victim's shard serving (both its HTTP shard endpoint
        # and its gRPC plane): reads touching its shards now reconstruct
        req = urllib.request.Request(
            f"http://{victim}/admin/faults",
            data=json.dumps({"set": [
                {"point": "ec.shard_read", "action": "error"},
                {"point": "rpc.VolumeEcShardRead", "action": "error"},
            ]}).encode(),
            headers={"Content-Type": "application/json"})
        urllib.request.urlopen(req, timeout=10).close()
        degraded = measure(n_reads)
        out.update({
            "n_reads": len(degraded),
            "degraded_p50_ms": pctl(degraded, 0.50),
            "degraded_p99_ms": pctl(degraded, 0.99),
            "degraded_over_healthy_p50": round(
                pctl(degraded, 0.50) / max(out["healthy_p50_ms"], 1e-6),
                2),
            "faulted_holder": victim,
            "note": ("one shard holder answers every shard read with an "
                     "injected error (fault plane, per-process "
                     "registry); reads served by another holder "
                     "reconstruct missing intervals from survivors"),
        })
        _phase_checkpoint(work, "degraded", out)
    finally:
        for p in procs:
            try:
                p.terminate()
            except OSError:
                pass
        for p in procs:
            try:
                p.wait(timeout=5)
            except Exception:
                try:
                    p.kill()
                except OSError:
                    pass
    return out


def _reader_storm(vport: int, fids: list, n_fg: int, n_bg: int,
                  seconds: float, breaker=None) -> dict:
    """Closed-loop reader storm against the volume fastpath
    (shared by phase_overload and phase_georepl — the georepl
    acceptance measures replication lag under exactly the
    overload phase's 3x-offered saturation shape).

    fg workers ride raw keep-alive connections and never honor
    Retry-After (they ARE the overload); bg workers go through
    HttpPool so shed answers exercise the breaker-exemption
    path."""
    import http.client as http_client
    import random as random_mod
    import threading

    from seaweedfs_tpu.cache.http_pool import HttpPool
    results: list = []
    lock = threading.Lock()
    stop_at = time.perf_counter() + seconds
    pool = HttpPool(breaker=breaker, shed_retries=0) \
        if n_bg else None

    def fg_worker(seed: int) -> None:
        r = random_mod.Random(seed)
        conn = None
        codes: dict = {}
        lat: list = []
        while time.perf_counter() < stop_at:
            fid = fids[r.randrange(len(fids))]
            t0 = time.perf_counter()
            try:
                if conn is None:
                    conn = http_client.HTTPConnection(
                        "127.0.0.1", vport, timeout=10)
                conn.request("GET", f"/{fid}")
                resp = conn.getresponse()
                resp.read()
                code = resp.status
                if resp.will_close:
                    conn.close()
                    conn = None
            except Exception:
                if conn is not None:
                    conn.close()
                conn = None
                continue
            codes[code] = codes.get(code, 0) + 1
            if code == 200:
                lat.append(time.perf_counter() - t0)
            else:
                # hold the offered rate instead of amplifying
                # it: an instantly-answered 503 re-sent in a
                # tight loop would turn "3x offered" into an
                # unbounded retry storm whose client threads
                # also starve the single-core server of CPU —
                # exactly the anti-pattern Retry-After exists
                # to prevent
                time.sleep(0.05)
        if conn is not None:
            conn.close()
        with lock:
            results.append(("fg", codes, lat))

    def bg_worker(seed: int) -> None:
        r = random_mod.Random(seed)
        codes: dict = {}
        while time.perf_counter() < stop_at:
            fid = fids[r.randrange(len(fids))]
            try:
                resp = pool.request(
                    "GET", f"http://127.0.0.1:{vport}/{fid}",
                    headers={"X-Seaweed-Priority": "bg"},
                    timeout=10)
                codes[resp.status] = codes.get(resp.status,
                                               0) + 1
            except Exception:
                continue
            time.sleep(0.01)  # repair-ish pacing, still pushy
        with lock:
            results.append(("bg", codes, {}))

    threads = [threading.Thread(target=fg_worker, args=(i,))
               for i in range(n_fg)]
    threads += [threading.Thread(target=bg_worker,
                                 args=(1000 + i,))
                for i in range(n_bg)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if pool is not None:
        pool.close()
    fg_codes: dict = {}
    bg_codes: dict = {}
    fg_lat: list = []
    for cls, codes, lat in results:
        tgt = fg_codes if cls == "fg" else bg_codes
        for k, v in codes.items():
            tgt[k] = tgt.get(k, 0) + v
        fg_lat.extend(lat)
    fg_lat.sort()

    def pctl(q: float) -> float:
        if not fg_lat:
            return 0.0
        return round(fg_lat[min(len(fg_lat) - 1,
                                int(len(fg_lat) * q))] * 1e3, 3)

    return {
        "goodput_req_s": round(fg_codes.get(200, 0) / seconds,
                               1),
        "fg_codes": {str(k): v for k, v in
                     sorted(fg_codes.items())},
        "bg_codes": {str(k): v for k, v in
                     sorted(bg_codes.items())},
        "p50_ms": pctl(0.50),
        "p99_ms": pctl(0.99),
    }


def phase_overload(work: str, budget_s: float = 150.0) -> dict:
    """Admitted goodput and p99 at >=2x offered saturation — the
    overload plane's headline numbers. A combined server boots with a
    deliberately small foreground pipe (WEED_ADMISSION_FG_CONCURRENCY=8,
    queue 8) and a 20ms injected service time on volume reads (fault
    plane — same delay in both phases, so capacity is identical and the
    ratio is honest). Phase A saturates the pipe exactly (8 closed-loop
    readers = capacity); phase B offers 3x that (24 fg readers + 4
    bg-tagged readers). Acceptance: admitted goodput under overload
    >= 85% of the single-saturation peak, zero bg requests admitted
    while fg is being shed (server-side inversion counter AND
    client-side observation), and no circuit breaker opened by shed
    responses (bg riders use a threshold-1 breaker)."""
    import random as random_mod
    import socket
    import urllib.request

    started = time.perf_counter()

    def left() -> float:
        return budget_s - (time.perf_counter() - started)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from seaweedfs_tpu.client import Client
    from seaweedfs_tpu.utils.retry import CircuitBreaker

    import seaweedfs_tpu
    pkg_root = os.path.dirname(os.path.dirname(seaweedfs_tpu.__file__))
    env = dict(os.environ, JAX_PLATFORMS="cpu", SEAWEEDFS_FORCE_CPU="1",
               WEED_ADMISSION_FG_CONCURRENCY="8",
               WEED_ADMISSION_FG_QUEUE="8",
               WEED_ADMISSION_QUEUE_TIMEOUT_MS="2000",
               WEED_ADMISSION_BG_CONCURRENCY="4",
               WEED_ADMISSION_RETRY_AFTER_S="1")
    env["PYTHONPATH"] = pkg_root + os.pathsep + env.get("PYTHONPATH", "")

    def free_port() -> int:
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    mport, vport = free_port(), free_port()
    data_dir = os.path.join(work, "overload_srv")
    os.makedirs(data_dir, exist_ok=True)
    logf = open(os.path.join(work, "overload_srv.log"), "ab")
    proc = subprocess.Popen(
        [sys.executable, "-m", "seaweedfs_tpu.cli", "server",
         "-ip", "127.0.0.1", "-master_port", str(mport),
         "-port", str(vport), "-dir", data_dir],
        cwd=data_dir, env=env, stdout=logf, stderr=logf)
    out: dict = {}
    try:
        deadline = time.time() + 45
        while True:
            try:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{mport}/dir/assign",
                        timeout=2) as r:
                    if "fid" in json.loads(r.read()):
                        break
            except Exception:
                pass
            if time.time() > deadline:
                raise RuntimeError("overload server failed to start")
            time.sleep(0.3)

        client = Client(f"127.0.0.1:{mport}")
        rng = random_mod.Random(13)
        fids = [client.upload(bytes(rng.getrandbits(8)
                                    for _ in range(1024)))
                for _ in range(64)]

        # 20ms injected service time on the volume read path — the knob
        # that makes capacity deterministic (8 slots / ~21.5ms ~= 370
        # req/s) AND leaves CPU headroom on the shared host, so the
        # overload phase measures the admission queue, not GIL
        # contention between the storm threads and the server process
        req = urllib.request.Request(
            f"http://127.0.0.1:{vport}/admin/faults",
            data=json.dumps({"set": [
                {"point": "volume.read", "action": "delay", "ms": 20},
            ]}).encode(),
            headers={"Content-Type": "application/json"})
        urllib.request.urlopen(req, timeout=10).close()

        peak = _reader_storm(vport, fids, 8, 0,
                             min(4.0, max(left() - 30, 2.0)))
        out["peak"] = peak
        _phase_checkpoint(work, "overload", out)

        breaker = CircuitBreaker(failure_threshold=1)
        over = _reader_storm(vport, fids, 24, 4,
                             min(5.0, max(left() - 15, 2.0)),
                             breaker=breaker)
        out["overload"] = over
        out["offered_factor"] = 3.0  # 24 closed-loop readers vs 8
        peak_good = max(peak["goodput_req_s"], 1e-6)
        out["goodput_ratio"] = round(
            over["goodput_req_s"] / peak_good, 3)
        out["fg_shed"] = over["fg_codes"].get("503", 0)
        out["bg_shed"] = over["bg_codes"].get("503", 0)
        out["bg_admitted_during_storm"] = over["bg_codes"].get("200", 0)
        out["client_breaker_opened"] = breaker.is_open(
            f"127.0.0.1:{vport}")
        _phase_checkpoint(work, "overload", out)

        # server-side evidence from /metrics: the inversion counter
        # (bg admitted under fg pressure — must not exist/stay 0) and
        # breaker_opened (shed answers must not have tripped anything)
        with urllib.request.urlopen(
                f"http://127.0.0.1:{vport}/metrics", timeout=10) as r:
            text = r.read().decode()

        def metric(needle: str) -> float:
            for line in text.splitlines():
                if needle in line and not line.startswith("#"):
                    try:
                        return float(line.rsplit(" ", 1)[1])
                    except ValueError:
                        pass
            return 0.0

        out["server_metrics"] = {
            "admitted_fg": metric('admission_admitted_total{cls="fg"}'),
            "admitted_bg": metric('admission_admitted_total{cls="bg"}'),
            "shed_fg": metric('admission_shed_total{cls="fg"}'),
            "shed_bg": metric('admission_shed_total{cls="bg"}'),
            "inversions": metric("admission_inversion_total"),
            "breaker_opened": metric("breaker_opened_total"),
        }
        out["acceptance"] = {
            "goodput_ge_85pct_of_peak": out["goodput_ratio"] >= 0.85,
            # judged by the server's invariant counter (bg admitted WHILE
            # fg pressure exists, checked at admit time) — a whole-window
            # client-side count would flag a bg 200 that legitimately
            # landed before fg pressure formed at storm start;
            # bg_admitted_during_storm stays above as informational
            "zero_bg_admitted_while_fg_shed":
                out["server_metrics"]["inversions"] == 0,
            "no_breaker_opened_by_shed":
                out["server_metrics"]["breaker_opened"] == 0
                and not out["client_breaker_opened"],
        }
        _phase_checkpoint(work, "overload", out)
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()
        logf.close()
        time.sleep(0.5)
    return out



def phase_observe(work: str, budget_s: float = 180.0) -> dict:
    """Telemetry-plane overhead gate: read p50 with the whole plane
    armed (19Hz sampling profiler + per-request wide events + trace
    spans — the shipping default) vs fully disarmed, same server shape
    and workload. Acceptance: armed p50 regression <= 3% — the number
    that justifies always-on in production. Each config boots its own
    server (the knobs are read at startup) and is measured twice with
    the min taken, so a one-off host hiccup can't fail the gate.

    Both configs get the same fault-injected 2ms service time on
    volume.read (phase_overload's determinism trick): without a floor
    the raw p50 on this host is ~0.8ms and swings +-25% run to run from
    scheduler noise alone — far above the ~10us/request the plane
    actually costs (measured separately and reported as
    per_request_overhead_us, so the absolute cost stays visible and
    isn't laundered by the floor)."""
    import socket
    import urllib.request

    started = time.perf_counter()

    def left() -> float:
        return budget_s - (time.perf_counter() - started)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from seaweedfs_tpu.client import Client

    import seaweedfs_tpu
    pkg_root = os.path.dirname(os.path.dirname(seaweedfs_tpu.__file__))

    def free_port() -> int:
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    def measure(tag: str, env_extra: dict, armed: bool = False) -> dict:
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   SEAWEEDFS_FORCE_CPU="1", **env_extra)
        env["PYTHONPATH"] = pkg_root + os.pathsep + env.get(
            "PYTHONPATH", "")
        mport, vport = free_port(), free_port()
        data_dir = os.path.join(work, f"observe_{tag}")
        os.makedirs(data_dir, exist_ok=True)
        with open(os.path.join(work, f"observe_{tag}.log"), "ab") as logf:
            proc = subprocess.Popen(
                [sys.executable, "-m", "seaweedfs_tpu.cli", "server",
                 "-ip", "127.0.0.1", "-master_port", str(mport),
                 "-port", str(vport), "-dir", data_dir],
                cwd=data_dir, env=env, stdout=logf, stderr=logf)
            try:
                deadline = time.time() + 45
                while True:
                    try:
                        with urllib.request.urlopen(
                                f"http://127.0.0.1:{mport}/dir/assign",
                                timeout=2) as r:
                            if "fid" in json.loads(r.read()):
                                break
                    except Exception:
                        pass
                    if time.time() > deadline:
                        raise RuntimeError(
                            f"observe/{tag} server failed to start")
                    time.sleep(0.3)
                client = Client(f"127.0.0.1:{mport}")
                fids = [client.upload(b"telemetry overhead " * 50)
                        for _ in range(32)]
                req = urllib.request.Request(
                    f"http://127.0.0.1:{vport}/admin/faults",
                    data=json.dumps({"set": [
                        {"point": "volume.read", "action": "delay",
                         "ms": 2},
                    ]}).encode(),
                    headers={"Content-Type": "application/json"})
                urllib.request.urlopen(req, timeout=10).close()
                # 2 closed-loop readers (not more): the storm threads
                # share this process's GIL, and their own scheduling
                # noise at higher counts dwarfs the ~10us/request being
                # measured. min over several storms estimates the
                # interference-free p50 (min-statistics: noise is
                # strictly additive here)
                secs = min(3.0, max(left() / 16.0, 1.5))
                _reader_storm(vport, fids, 2, 0, secs)  # warm
                runs = [_reader_storm(vport, fids, 2, 0, secs)
                        for _ in range(4)]
                best = min(runs, key=lambda r: r["p50_ms"] or 1e9)
                res = {"p50_ms": best["p50_ms"],
                       "p99_ms": best["p99_ms"],
                       "goodput_req_s": best["goodput_req_s"]}
                if armed:
                    # prove the plane was actually live while measured
                    with urllib.request.urlopen(
                            f"http://127.0.0.1:{vport}/debug/pprof"
                            "?format=stats", timeout=10) as r:
                        res["profiler"] = json.loads(r.read())
                    with urllib.request.urlopen(
                            f"http://127.0.0.1:{vport}/debug/events"
                            "?limit=1", timeout=10) as r:
                        res["wide_events_seen"] = json.loads(
                            r.read())["count"]
                return res
            finally:
                proc.terminate()
                try:
                    proc.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    proc.kill()
                time.sleep(0.5)

    configs = {"off": {"WEED_PROFILE": "0", "WEED_WIDE_EVENTS": "0"},
               "armed": {"WEED_PROFILE": "1", "WEED_WIDE_EVENTS": "1"}}
    # alternate boots (off, armed, off, armed): server-process placement
    # varies boot to boot, and a drifting host biases any
    # all-of-A-then-all-of-B ordering; min across boots cancels it
    out: dict = {}
    rounds: dict = {"off": [], "armed": []}
    for rnd in range(2):
        if rnd == 1 and left() < 40:
            break
        for tag, env_extra in configs.items():
            rounds[tag].append(measure(f"{tag}{rnd}", env_extra,
                                       armed=(tag == "armed")))
            _phase_checkpoint(work, "observe",
                              {**out, "rounds": rounds})
    for tag in configs:
        out[tag] = min(rounds[tag], key=lambda r: r["p50_ms"] or 1e9)
    out["boots_per_config"] = len(rounds["off"])
    out["round_p50s"] = {tag: [r["p50_ms"] for r in rs]
                         for tag, rs in rounds.items()}
    p50_off = out["off"]["p50_ms"] or 1e-9
    out["p50_regression_pct"] = round(
        (out["armed"]["p50_ms"] - p50_off) / p50_off * 100.0, 2)
    out["per_request_overhead_us"] = round(
        (out["armed"]["p50_ms"] - p50_off) * 1000.0, 1)
    out["acceptance"] = {
        "plane_live_while_measured":
            out["armed"].get("profiler", {}).get("samples", 0) > 0
            and out["armed"].get("wide_events_seen", 0) > 0,
        "p50_regression_le_3pct": out["p50_regression_pct"] <= 3.0,
    }
    _phase_checkpoint(work, "observe", out)
    return out


def phase_georepl(work: str, budget_s: float = 240.0) -> dict:
    """Cluster-to-cluster replication lag: steady-state vs under the
    overload storm.  Two combined servers (master+volume+filer) boot as
    separate clusters; the primary's geo daemon replicates bucket "geo"
    to the replica per a PutBucketReplication-shaped rule.  Lag is
    measured end-to-end with PROBE objects: write through the primary
    filer, poll the replica filer until the bytes are visible — no
    trust in internal gauges.  The storm phase replays phase_overload's
    3x-offered saturation (_reader_storm, 24 closed-loop fg readers
    against the primary volume fastpath with a 20ms injected service
    time) while probes keep flowing.  Acceptance: storm-phase median
    lag <= 2x steady-state median (floored at 0.25s — sub-100ms medians
    make the ratio noise), zero priority inversions at the primary
    (replication traffic is CLASS_BG and must shed first, never
    displace fg), zero poisoned events."""
    import socket
    import threading
    import urllib.request

    started = time.perf_counter()

    def left() -> float:
        return budget_s - (time.perf_counter() - started)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from seaweedfs_tpu.client import Client

    import seaweedfs_tpu
    pkg_root = os.path.dirname(os.path.dirname(seaweedfs_tpu.__file__))

    def free_port() -> int:
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    pm, pv, pf = free_port(), free_port(), free_port()
    rm, rv, rf = free_port(), free_port(), free_port()
    base_env = dict(os.environ, JAX_PLATFORMS="cpu",
                    SEAWEEDFS_FORCE_CPU="1")
    base_env["PYTHONPATH"] = pkg_root + os.pathsep + \
        base_env.get("PYTHONPATH", "")
    # the primary gets the small fg pipe + the geo daemon; the replica
    # is a plain cluster
    prim_env = dict(base_env,
                    WEED_GEO_FILER=f"127.0.0.1:{pf}",
                    WEED_GEO_INTERVAL="0.5",
                    WEED_ADMISSION_FG_CONCURRENCY="8",
                    WEED_ADMISSION_FG_QUEUE="8",
                    WEED_ADMISSION_QUEUE_TIMEOUT_MS="2000",
                    WEED_ADMISSION_BG_CONCURRENCY="4",
                    WEED_ADMISSION_RETRY_AFTER_S="1")

    def boot(tag: str, env: dict, mport: int, vport: int,
             fport: int):
        data_dir = os.path.join(work, f"georepl_{tag}")
        os.makedirs(data_dir, exist_ok=True)
        logf = open(os.path.join(work, f"georepl_{tag}.log"), "ab")
        proc = subprocess.Popen(
            [sys.executable, "-m", "seaweedfs_tpu.cli", "server",
             "-ip", "127.0.0.1", "-master_port", str(mport),
             "-port", str(vport), "-dir", data_dir,
             "-filer", "-filer_port", str(fport),
             "-filer_db", os.path.join(data_dir, "filer.db")],
            cwd=data_dir, env=env, stdout=logf, stderr=logf)
        return proc, logf

    def wait_up(mport: int, fport: int) -> None:
        deadline = time.time() + 60
        while True:
            try:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{mport}/dir/assign",
                        timeout=2) as r:
                    if "fid" in json.loads(r.read()):
                        with urllib.request.urlopen(
                                f"http://127.0.0.1:{fport}/healthz",
                                timeout=2):
                            return
            except Exception:
                pass
            if time.time() > deadline:
                raise RuntimeError("georepl cluster failed to start")
            time.sleep(0.3)

    def http(method: str, url: str, body=None, headers=None):
        req = urllib.request.Request(url, data=body, method=method,
                                     headers=headers or {})
        with urllib.request.urlopen(req, timeout=20) as r:
            return r.status, r.read()

    def filer_put(fport: int, path: str, data: bytes) -> None:
        http("PUT", f"http://127.0.0.1:{fport}{path}", data,
             {"Content-Type": "application/octet-stream"})

    def replica_has(path: str, want: bytes) -> bool:
        try:
            return http("GET",
                        f"http://127.0.0.1:{rf}{path}")[1] == want
        except Exception:
            return False

    out: dict = {}
    prim, prim_log = boot("primary", prim_env, pm, pv, pf)
    repl, repl_log = boot("replica", base_env, rm, rv, rf)
    try:
        wait_up(pm, pf)
        wait_up(rm, rf)
        # bucket on both sides + the replication rule on the primary's
        # bucket entry (the JSON the S3 PutBucketReplication route
        # stores; set via the meta API so the phase needs no gateway)
        for fport in (pf, rf):
            http("POST",
                 f"http://127.0.0.1:{fport}/buckets/geo?op=mkdir")
        rule = [{"id": "bench", "status": "Enabled", "prefix": "",
                 "dest_bucket": "geo",
                 "endpoint": f"127.0.0.1:{rf}"}]
        entry = {"path": "/buckets/geo",
                 "attr": {"mode": 0o40770, "mtime": time.time(),
                          "crtime": time.time()},
                 "chunks": [],
                 "extended": {"seaweed-replication":
                              json.dumps(rule, sort_keys=True)}}
        http("POST", f"http://127.0.0.1:{pf}/__meta__/create_entry",
             json.dumps({"entry": entry}).encode(),
             {"Content-Type": "application/json"})
        http("POST", f"http://127.0.0.1:{pm}/geo/run",
             json.dumps({}).encode(),
             {"Content-Type": "application/json"})

        rng = __import__("random").Random(17)
        blob = bytes(rng.getrandbits(8) for _ in range(4096))

        def probe_lag(tag: str, n: int, spacing: float) -> list:
            """Replication lag per probe: time from the primary WRITE
            COMPLETING to the bytes being readable on the replica.  A
            shed PUT (the storm saturates the fg pipe; the filer
            answers 502/503) is retried like any cooperative client
            would — that admission wait is the overload plane's number
            (phase_overload p99), not geo lag, so the lag clock starts
            when the write lands."""
            lags = []
            for i in range(n):
                path = f"/buckets/geo/{tag}{i:03d}"
                t_first = time.perf_counter()
                put_ok = False
                while True:
                    try:
                        filer_put(pf, path, blob)
                        put_ok = True
                        break
                    except Exception:
                        if time.perf_counter() - t_first > 30:
                            break
                        time.sleep(0.1)
                if not put_ok:
                    lags.append(30.0)  # the WRITE never landed
                    continue
                t0 = time.perf_counter()
                while not replica_has(path, blob):
                    if time.perf_counter() - t0 > 30:
                        lags.append(30.0)  # loudly saturated, not lost
                        break
                    time.sleep(0.02)
                else:
                    lags.append(time.perf_counter() - t0)
                time.sleep(spacing)
            return lags

        def med(xs: list) -> float:
            ys = sorted(xs)
            return ys[len(ys) // 2] if ys else 0.0

        # steady state
        steady = probe_lag("s", 10, 0.2)
        out["steady_lag_s"] = {
            "median": round(med(steady), 3),
            "max": round(max(steady), 3),
            "samples": [round(x, 3) for x in steady]}
        _phase_checkpoint(work, "georepl", out)

        # the overload storm: 20ms injected volume.read service time +
        # 24 closed-loop fg readers = phase_overload's 3x-offered shape
        client = Client(f"127.0.0.1:{pm}")
        fids = [client.upload(blob[:1024]) for _ in range(32)]
        http("POST", f"http://127.0.0.1:{pv}/admin/faults",
             json.dumps({"set": [{"point": "volume.read",
                                  "action": "delay",
                                  "ms": 20}]}).encode(),
             {"Content-Type": "application/json"})
        storm_secs = min(10.0, max(left() - 40, 4.0))
        storm_out: dict = {}

        def run_storm() -> None:
            storm_out.update(_reader_storm(pv, fids, 24, 0,
                                           storm_secs))

        storm_thread = threading.Thread(target=run_storm)
        storm_thread.start()
        time.sleep(0.3)  # let the storm form before probing
        stormy = probe_lag("o", 8, 0.1)
        storm_thread.join()
        http("POST", f"http://127.0.0.1:{pv}/admin/faults",
             json.dumps({"clear": "*"}).encode(),
             {"Content-Type": "application/json"})
        out["storm"] = storm_out
        out["storm_lag_s"] = {
            "median": round(med(stormy), 3),
            "max": round(max(stormy), 3),
            "samples": [round(x, 3) for x in stormy]}
        _phase_checkpoint(work, "georepl", out)

        # evidence: inversions + geo job state
        with urllib.request.urlopen(
                f"http://127.0.0.1:{pv}/metrics", timeout=10) as r:
            vol_metrics = r.read().decode()
        inversions = 0.0
        for line in vol_metrics.splitlines():
            if line.startswith("admission_inversion_total"):
                inversions = float(line.rsplit(" ", 1)[1])
        with urllib.request.urlopen(
                f"http://127.0.0.1:{pm}/geo/status", timeout=10) as r:
            geo_status = json.loads(r.read())
        job = (geo_status.get("jobs") or {}).get("geo", {})
        out["geo_job"] = {k: job.get(k) for k in
                          ("applied", "skipped", "poisoned", "state",
                           "lag_s")}
        out["inversions"] = inversions
        steady_floor = max(out["steady_lag_s"]["median"], 0.25)
        out["lag_ratio"] = round(
            out["storm_lag_s"]["median"] / steady_floor, 3)
        out["acceptance"] = {
            "storm_lag_le_2x_steady": out["lag_ratio"] <= 2.0,
            "zero_inversions": inversions == 0,
            "zero_poisoned": (job.get("poisoned") or 0) == 0,
        }
        _phase_checkpoint(work, "georepl", out)
    finally:
        for proc, logf in ((prim, prim_log), (repl, repl_log)):
            proc.terminate()
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
            logf.close()
        time.sleep(0.5)
    return out


def phase_lifecycle(work: str, budget_s: float = 240.0,
                    n_idle: int = 6) -> dict:
    """Time-to-warm for a batch of idle volumes under the lifecycle
    daemon, with proof the hot path doesn't degrade while transitions
    run. A real multi-process cluster (master + 4 volume servers) boots
    with the lifecycle knobs compressed — WEED_LIFECYCLE_WARM_AFTER=5s
    and a near-zero FULL_FRACTION artificially age every seeded volume
    — then `n_idle` single-volume collections are seeded and left
    alone while one "hot" collection is read in a closed loop the
    whole time (which also keeps it off the warm path: idleness, not
    just fullness, gates the transition). The daemon seals, vacuums,
    EC-encodes, and spreads every idle volume with ZERO operator
    commands; we record each volume's time from seeding to 14/14
    shards, and compare hot-read p50 measured before the first
    transition against p50 measured while they run. Budget-aware and
    checkpointed into lifecycle_partial.json like the other phases."""
    import random as random_mod
    import socket
    import urllib.request

    started = time.perf_counter()

    def left() -> float:
        return budget_s - (time.perf_counter() - started)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from seaweedfs_tpu.client import Client

    import seaweedfs_tpu
    pkg_root = os.path.dirname(os.path.dirname(seaweedfs_tpu.__file__))
    WARM_AFTER_S = 5.0
    env = dict(os.environ, JAX_PLATFORMS="cpu", SEAWEEDFS_FORCE_CPU="1",
               WEED_LIFECYCLE_WARM_AFTER=f"{WARM_AFTER_S:.0f}",
               WEED_LIFECYCLE_INTERVAL="0.5",
               # any volume holding data counts as sealed: the bench
               # ages volumes by compressing the clock, not by writing
               # 30GB each
               WEED_LIFECYCLE_FULL_FRACTION="0.000001")
    env["PYTHONPATH"] = pkg_root + os.pathsep + env.get("PYTHONPATH", "")

    def free_port() -> int:
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    def spawn(args, tag):
        log = open(os.path.join(work, f"lifecycle_{tag}.log"), "ab")
        return subprocess.Popen(
            [sys.executable, "-m", "seaweedfs_tpu.cli"] + args,
            cwd=work, env=env, stdout=log, stderr=log)

    procs = []
    out: dict = {"n_idle_volumes": n_idle,
                 "warm_after_s": WARM_AFTER_S}
    try:
        mport = free_port()
        master = f"127.0.0.1:{mport}"
        procs.append(spawn(["master", "-port", str(mport), "-mdir", work],
                           "master"))
        for i in range(4):
            vdir = os.path.join(work, f"lifecycle_vs{i}")
            os.makedirs(vdir, exist_ok=True)
            procs.append(spawn(["volume", "-port", str(free_port()),
                                "-dir", vdir, "-mserver", master,
                                "-pulse", "1"], f"vs{i}"))
        client = Client(master)
        deadline = time.time() + 45
        while time.time() < deadline:
            try:
                if len(client.dir_status().get("nodes", [])) >= 4:
                    break
            except Exception:
                pass
            time.sleep(0.3)

        rng = random_mod.Random(7)
        # the hot set: small blobs read in a closed loop throughout
        hot_blobs: dict[str, bytes] = {}
        for _ in range(16):
            data = bytes(rng.getrandbits(8) for _ in range(4096))
            hot_blobs[client.upload(data, collection="hot")] = data
        hot_fids = list(hot_blobs)
        hot_vids = {int(f.split(",")[0]) for f in hot_fids}
        hot_urls = {v: client.lookup(v)[0] for v in hot_vids}

        # the idle batch: one collection per volume, 2x48KB random
        # (incompressible) blobs each — enough to cross the sealed bar
        idle_blobs: dict[str, bytes] = {}
        for i in range(n_idle):
            for _ in range(2):
                data = bytes(rng.getrandbits(8) for _ in range(48 * 1024))
                idle_blobs[client.upload(data, collection=f"lc{i}")] = data
        idle_vids = sorted({int(f.split(",")[0]) for f in idle_blobs})
        t_seeded = time.time()
        out["seeded_idle_vids"] = idle_vids
        _phase_checkpoint(work, "lifecycle", out)

        def hot_read_once() -> float:
            fid = hot_fids[rng.randrange(len(hot_fids))]
            vid = int(fid.split(",")[0])
            t0 = time.perf_counter()
            with urllib.request.urlopen(
                    f"http://{hot_urls[vid]}/{fid}", timeout=30) as r:
                body = r.read()
            dt = time.perf_counter() - t0
            assert body == hot_blobs[fid], f"corrupt hot read of {fid}"
            return dt

        def pctl(lat: list[float], q: float) -> float:
            return round(
                sorted(lat)[min(len(lat) - 1, int(len(lat) * q))] * 1e3, 3)

        def shard_count(vid: int) -> int:
            try:
                return len(client.ec_lookup(vid).get("shards", {}))
            except Exception:
                return 0

        # baseline hot p50: the warm window hasn't elapsed yet, so no
        # transition can be running while this samples
        before: list[float] = []
        while time.time() - t_seeded < WARM_AFTER_S - 1.5 and left() > 60:
            before.append(hot_read_once())
        out["hot_p50_before_ms"] = pctl(before, 0.50) if before else None
        out["hot_p99_before_ms"] = pctl(before, 0.99) if before else None
        _phase_checkpoint(work, "lifecycle", out)

        # now the daemon takes over: keep hammering the hot set (which
        # also keeps it off the warm path) and record when each idle
        # volume reaches the full shard set
        during: list[float] = []
        warm_at: dict[int, float] = {}
        next_poll = 0.0
        while len(warm_at) < len(idle_vids) and left() > 25:
            during.append(hot_read_once())
            if time.time() < next_poll:
                continue
            next_poll = time.time() + 0.3
            for vid in idle_vids:
                if vid not in warm_at and shard_count(vid) >= 14:
                    warm_at[vid] = time.time() - t_seeded
        warmed = sorted(warm_at.values())
        out.update({
            "warmed_volumes": len(warm_at),
            "time_to_warm_first_s": round(warmed[0], 2) if warmed
            else None,
            "time_to_warm_p50_s": round(
                warmed[len(warmed) // 2], 2) if warmed else None,
            "time_to_warm_all_s": round(warmed[-1], 2) if warmed
            else None,
            "hot_p50_during_ms": pctl(during, 0.50) if during else None,
            "hot_p99_during_ms": pctl(during, 0.99) if during else None,
            "hot_reads_sampled": len(before) + len(during),
        })
        if before and during:
            out["hot_p50_ratio"] = round(
                out["hot_p50_during_ms"]
                / max(out["hot_p50_before_ms"], 1e-6), 2)
        _phase_checkpoint(work, "lifecycle", out)

        # every blob is still readable from the warm tier
        client._vid_cache.clear()
        for fid, data in idle_blobs.items():
            assert client.download(fid) == data, \
                f"blob {fid} lost through the warm transition"

        with urllib.request.urlopen(f"http://{master}/metrics",
                                    timeout=10) as r:
            text = r.read().decode()

        def metric(needle: str) -> float:
            for line in text.splitlines():
                if needle in line and not line.startswith("#"):
                    try:
                        return float(line.rsplit(" ", 1)[1])
                    except ValueError:
                        pass
            return 0.0

        out["server_metrics"] = {
            "transitions_warm_ok": metric(
                'lifecycle_transitions_total{kind="warm",outcome="ok"}'),
            "transitions_warm_failed": metric(
                'lifecycle_transitions_total'
                '{kind="warm",outcome="failed"}'),
        }
        out["acceptance"] = {
            "all_idle_volumes_warmed":
                len(warm_at) == len(idle_vids),
            # "unchanged" within single-shared-host noise: the encodes
            # run on the same CPUs as the reads, so allow 2x on p50
            "hot_p50_within_2x":
                bool(before and during and out["hot_p50_ratio"] <= 2.0),
            "warm_data_intact": True,  # the asserts above would throw
        }
        out["note"] = (
            "time-to-warm counts from the last seed write to 14/14 "
            "shards visible in ec_lookup; the daemon sealed, vacuumed, "
            "encoded, and spread every volume itself (zero operator "
            "commands, WEED_LIFECYCLE_WARM_AFTER=5s, bg-class "
            "transitions bounded by the repair semaphore). Hot p50 is "
            "measured on direct volume-server GETs of a collection "
            "kept hot by the same closed loop.")
        _phase_checkpoint(work, "lifecycle", out)
    finally:
        for p in procs:
            try:
                p.terminate()
            except OSError:
                pass
        for p in procs:
            try:
                p.wait(timeout=5)
            except Exception:
                try:
                    p.kill()
                except OSError:
                    pass
    return out


def phase_multichip(work: str, budget_s: float = 240.0) -> dict:
    """Mesh-sharded encode/rebuild fabric on the 8-device virtual CPU
    mesh (the MULTICHIP dryrun substrate, now through the PRODUCTION
    MeshCoder + pipeline instead of the kernel demo).

    What each number means — and what the substrate can and cannot
    show:

      * aggregate_wall_gbps[n]: real wall-clock aggregate of the mesh
        path at mesh size n, weak-scaled workload (n * per-chip bytes).
        Virtual CPU devices SHARE the host's cores (one XLA device
        already saturates the machine), so this curve is flat-ish here
        by construction; on ICI-attached chips each device is its own
        silicon and the wall curve IS the projection below.
      * per_chip_slice_gbps[n]: measured single-device rate at exactly
        the per-chip slice width mesh size n deals each device.
      * fabric_overhead[n]: mesh-executable wall over n * single-device
        slice wall — the work the fabric ADDS (padding, resharding,
        collectives, dispatch serialization). ~1.0 means the shard_map
        program does per-chip work and nothing else.
      * aggregate_projected_gbps[n] = n * per_chip_slice_gbps[n]
        / fabric_overhead[n]: the aggregate on hardware where chips
        don't share cores. Valid exactly when collective_free holds —
        which is asserted from the compiled HLO, not assumed.

    Plus: shard byte-identity vs the single-chip striping layout at
    RS(10,4) AND RS(20,4) (odd batch width → padded shard_map path),
    and a simulated rack-loss rebuild storm (6 volumes) drained through
    the master's WEED_EC_ENCODE_WORKERS pool vs serial dispatch.
    """
    # must land BEFORE the first jax import in this process
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    import hashlib

    import jax

    from seaweedfs_tpu import ec
    from seaweedfs_tpu.ec import pipeline
    from seaweedfs_tpu.ec.coder import JaxCoder
    from seaweedfs_tpu.parallel import mesh_coder

    started = time.perf_counter()
    out: dict = {"backend": jax.default_backend(),
                 "devices": len(jax.devices())}
    _phase_checkpoint(work, "multichip", out)

    def left() -> float:
        return budget_s - (time.perf_counter() - started)

    # --- scaling curve: weak-scaled encode over mesh sizes 1/2/4/8 ---
    k, m = 10, 4
    per_chip_w = MB  # per-chip slice: [10, 1MB]
    reps = 3
    rng = np.random.default_rng(11)
    curve: dict = {}
    single = JaxCoder(k, m)
    for n in (1, 2, 4, 8):
        if left() < 30:
            curve[str(n)] = f"skipped: budget ({left():.0f}s left)"
            continue
        coder = mesh_coder.coder(k, m, n_devices=n)
        data = rng.integers(0, 256, (k, n * per_chip_w), dtype=np.uint8)
        slice_data = data[:, :per_chip_w]
        # mesh wall (includes per-chip staging)
        h = coder.encode_async(data)  # compile + warm
        np.asarray(getattr(h, "arr", h))
        t0 = time.perf_counter()
        for _ in range(reps):
            h = coder.encode_async(data)
        np.asarray(getattr(h, "arr", h))
        t_mesh = (time.perf_counter() - t0) / reps
        # single-device slice wall (the per-chip work at this mesh size)
        hs = single.encode_async(slice_data)
        np.asarray(hs)
        t0 = time.perf_counter()
        for _ in range(reps):
            hs = single.encode_async(slice_data)
        np.asarray(hs)
        t_dev = (time.perf_counter() - t0) / reps
        per_chip_gbps = k * per_chip_w / t_dev / 1e9
        overhead = t_mesh / (n * t_dev) if n > 1 else t_mesh / t_dev
        projected = n * per_chip_gbps / max(overhead, 1e-9)
        curve[str(n)] = {
            "aggregate_wall_gbps": round(k * n * per_chip_w / t_mesh / 1e9,
                                         3),
            "per_chip_slice_gbps": round(per_chip_gbps, 3),
            "fabric_overhead": round(overhead, 3),
            "aggregate_projected_gbps": round(projected, 3),
        }
        out["scaling"] = curve
        _phase_checkpoint(work, "multichip", out)
    mesh8 = mesh_coder.coder(k, m, n_devices=min(8, len(jax.devices())))
    out["collective_free"] = bool(
        getattr(mesh8, "encode_is_collective_free", lambda: True)())
    _phase_checkpoint(work, "multichip", out)

    # --- byte-identity: mesh pipeline vs single-chip striping layout ---
    def _identity(geometry: "ec.Geometry", seed: int) -> bool:
        kk, mm = geometry.data_shards, geometry.parity_shards
        size = 61_007
        r = np.random.default_rng(seed)
        payload = r.integers(0, 256, size, dtype=np.uint8).tobytes()
        ref = os.path.join(work, f"mc_ref_{kk}_{mm}_1")
        mesh_base = os.path.join(work, f"mc_mesh_{kk}_{mm}_1")
        for base in (ref, mesh_base):
            with open(base + ".dat", "wb") as f:
                f.write(payload)
        ec.write_ec_files(ref, _host_coder_km(kk, mm), geometry,
                          buffer_size=100)
        # odd batch width: not divisible by the mesh -> padded path
        pipeline.stream_encode(mesh_base,
                               mesh_coder.coder(kk, mm,
                                                n_devices=min(
                                                    8, len(jax.devices()))),
                               geometry, batch_size=999)
        for i in range(geometry.total_shards):
            a = hashlib.sha256(
                open(ref + ec.to_ext(i), "rb").read()).hexdigest()
            b = hashlib.sha256(
                open(mesh_base + ec.to_ext(i), "rb").read()).hexdigest()
            if a != b:
                return False
        return True

    def _host_coder_km(kk: int, mm: int):
        try:
            return ec.get_coder("cpp", kk, mm)
        except Exception:
            return ec.get_coder("numpy", kk, mm)

    ident: dict = {}
    for label, g in (("10+4", ec.Geometry(10, 4, large_block_size=10000,
                                          small_block_size=100)),
                     ("20+4", ec.Geometry(20, 4, large_block_size=10000,
                                          small_block_size=100))):
        if left() < 30:
            ident[label] = f"skipped: budget ({left():.0f}s left)"
            continue
        try:
            ident[label] = bool(_identity(g, seed=len(label)))
        except Exception as e:
            ident[label] = f"error: {type(e).__name__}: {str(e)[:160]}"
        out["byte_identity"] = ident
        _phase_checkpoint(work, "multichip", out)

    # --- rebuild storm: worker pool vs serial dispatch ---
    if left() > 30:
        try:
            out["rebuild_storm"] = _multichip_storm()
        except Exception as e:
            out["rebuild_storm"] = {"error":
                                    f"{type(e).__name__}: {str(e)[:300]}"}
    else:
        out["rebuild_storm"] = f"skipped: budget ({left():.0f}s left)"
    _phase_checkpoint(work, "multichip", out)

    c = {n: v for n, v in curve.items() if isinstance(v, dict)}
    proj = {n: v["aggregate_projected_gbps"] for n, v in c.items()}
    storm = out.get("rebuild_storm")
    out["accept"] = {
        "collective_free": out.get("collective_free") is True,
        "scaling_1_to_2_ge_1p7": bool(
            proj.get("1") and proj.get("2")
            and proj["2"] / proj["1"] >= 1.7),
        "scaling_monotone_to_8": bool(
            len(proj) == 4
            and all(proj[str(2 * i)] >= 0.95 * proj[str(i)]
                    for i in (1, 2, 4))),
        "byte_identity_both_geometries": all(
            v is True for v in ident.values()) and len(ident) == 2,
        "storm_drain_under_0p6x_serial": bool(
            isinstance(storm, dict)
            and (storm.get("drain_ratio") or 9.9) < 0.6),
    }
    return out


def _multichip_storm(volumes: int = 6, rpc_s: float = 0.2) -> dict:
    """Rack-loss rebuild storm through the REAL master repair plumbing
    (planner, 2-pass deficit confirmation, semaphore pool, per-worker
    logs): 6 EC volumes short of shards, every rebuild RPC stubbed to a
    fixed service time (the master's wall time IS dispatch wait — the
    rebuild compute runs on the volume servers). Measures drain wall
    with the WEED_EC_ENCODE_WORKERS pool vs serial dispatch."""
    import asyncio

    from seaweedfs_tpu.cluster import raft as raft_mod
    from seaweedfs_tpu.server.master import MasterServer

    total = 14

    def build_master(workers: int) -> "MasterServer":
        master = MasterServer(repair_concurrency=workers,
                              maintenance_interval_seconds=3600.0)
        master.raft.role = raft_mod.LEADER
        # rack r2 died taking shards {3, 7, 11} of every volume with it
        # (11 survivors >= k=10, so each volume is rebuildable); racks
        # r0/r1 hold the survivors, r2's replacement node sits empty
        lost = {3, 7, 11}
        holdings = {0: [s for s in range(total)
                        if s not in lost and s % 2 == 0],
                    1: [s for s in range(total)
                        if s not in lost and s % 2 == 1],
                    2: []}
        for i in range(3):
            payload = {"volumes": [], "ec_shards": [
                {"id": vid, "collection": "",
                 "shard_ids": list(holdings[i])}
                for vid in range(1, volumes + 1)] if holdings[i] else []}
            master.topology.register_heartbeat(
                f"n{i}", f"127.0.0.1:{18080 + i}", "", "dc1", f"r{i}",
                100, payload)

        calls: list = []

        async def fake_admin_post(url, op, body, timeout=60.0):
            calls.append((url, op))
            await asyncio.sleep(rpc_s)
            if op == "ec/rebuild":
                return {"rebuilt": []}
            return {"ok": True}

        master._admin_post = fake_admin_post
        master._storm_calls = calls
        return master

    async def drain(workers: int) -> float:
        master = build_master(workers)
        await master._repair_pass()   # pass 1: deficit seen
        t0 = time.perf_counter()
        await master._repair_pass()   # pass 2: confirmed -> launch
        while master._repair_tasks:
            await asyncio.gather(*list(master._repair_tasks),
                                 return_exceptions=True)
        wall = time.perf_counter() - t0
        rebuilds = sum(1 for _, op in master._storm_calls
                       if op == "ec/rebuild")
        assert rebuilds == volumes, (rebuilds, volumes)
        return wall

    env_workers = os.environ.get("WEED_EC_ENCODE_WORKERS", "")
    try:
        pool = max(2, int(env_workers)) if env_workers else 4
    except ValueError:
        pool = 4
    serial_wall = asyncio.run(drain(1))
    pool_wall = asyncio.run(drain(pool))
    return {
        "volumes": volumes, "rebuild_rpc_s": rpc_s, "workers": pool,
        "serial_drain_s": round(serial_wall, 3),
        "pool_drain_s": round(pool_wall, 3),
        "drain_ratio": round(pool_wall / serial_wall, 3)
        if serial_wall > 1e-9 else None,
    }


_RING_BENCH_REPLICAS = 1


def _meta_noop() -> None:
    """Pool warm-up target (spawn + interpreter start happen here, not
    inside a timed row)."""


def _meta_driver_shard(pkg_root: str, peers: list, ring_dict,
                       op: str, n_dirs: int, indices: list,
                       threads: int, n_create: int) -> int:
    """One load-generator shard (its own PROCESS: a single GIL-bound
    driver saturates below three filer loops' capacity, so the client
    must scale out too).  Returns the shard's error count."""
    import http.client
    import threading as _threading
    from concurrent.futures import ThreadPoolExecutor

    sys.path.insert(0, pkg_root)
    ring = None
    if ring_dict is not None:
        from seaweedfs_tpu.metaring import DirectoryRing
        ring = DirectoryRing.from_dict(ring_dict)
    conns: dict = {}

    def conn_for(peer: str):
        key = (_threading.get_ident(), peer)
        c = conns.get(key)
        if c is None:
            host, _, port = peer.rpartition(":")
            c = http.client.HTTPConnection(host, int(port), timeout=20)
            conns[key] = c
        return c

    def req(peer: str, method: str, path: str, body=None) -> int:
        headers = {"Content-Type": "application/json"} if body else {}
        for _ in range(2):
            c = conn_for(peer)
            try:
                c.request(method, path, body=body, headers=headers)
                r = c.getresponse()
                r.read()
                return r.status
            except (http.client.HTTPException, OSError):
                c.close()
                conns.pop((_threading.get_ident(), peer), None)
        return 599

    def route(i: int) -> tuple:
        d = f"/bench/d{i % n_dirs}"
        if ring is None:
            return peers[0], d
        return ring.owner(d) or peers[0], d

    errors = [0]

    def one(i: int) -> None:
        peer, d = route(i)
        if op == "create":
            entry = {"path": f"{d}/f{i}.txt",
                     "attr": {"mtime": 1.0, "crtime": 1.0, "mode": 432,
                              "uid": 0, "gid": 0, "mime": "",
                              "ttl_sec": 0, "user_name": "",
                              "group_names": [], "symlink_target": "",
                              "md5": "", "replication": "",
                              "collection": ""},
                     "chunks": [], "extended": {}, "hard_link_id": ""}
            if req(peer, "POST", "/__meta__/create_entry",
                   json.dumps({"entry": entry}).encode()) != 200:
                errors[0] += 1
        elif op == "lookup":
            # probe entries the create section actually placed: file
            # j lives in d{j % n_dirs}, so the directory must derive
            # from the FILE index or most probes are negative lookups
            j = i % n_create
            dj = f"/bench/d{j % n_dirs}"
            pj = ring.owner(dj) if ring is not None else peers[0]
            if req(pj or peers[0], "GET",
                   f"/__meta__/lookup?path={dj}/f{j}.txt") != 200:
                errors[0] += 1
        else:
            if req(peer, "GET",
                   f"/__meta__/list?dir={d}&limit=128") != 200:
                errors[0] += 1

    with ThreadPoolExecutor(max_workers=threads) as pool:
        list(pool.map(one, indices))
    for c in conns.values():
        c.close()
    return errors[0]


def phase_metadata(work: str, budget_s: float = 240.0) -> dict:
    """Namespace-op throughput (metaring plane): create/lookup/list
    req/s against the filer meta API, one filer vs a 3-peer
    consistent-hash ring (each peer its own subprocess).  The driver is
    ring-aware — it fetches /dir/ring from the master and routes every
    op to the parent directory's owner, the smart-client shape
    production gateways use — so the 3-peer row measures partition
    scaling, not proxy-hop overhead.  Acceptance: 3-peer aggregate
    >= 1.8x single-peer."""
    global _RING_BENCH_REPLICAS
    import multiprocessing as mp
    import urllib.request

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from seaweedfs_tpu.metaring import DirectoryRing

    pkg_root = os.path.dirname(os.path.abspath(__file__))
    env = dict(os.environ, JAX_PLATFORMS="cpu", SEAWEEDFS_FORCE_CPU="1")
    env["PYTHONPATH"] = pkg_root + os.pathsep + env.get("PYTHONPATH", "")
    deadline = time.time() + budget_s

    # read-heavy mix (Haystack-shaped metadata traffic: reads dominate
    # writes by a wide margin); the load generator is 4 PROCESSES x 8
    # threads — one GIL-bound driver saturates below what three filer
    # loops serve
    N_CREATE, N_LOOKUP, N_LIST = 2000, 10000, 3000
    N_DIRS, PROCS, THREADS = 192, 6, 8

    def _wait_http(url: str, timeout: float = 30.0) -> None:
        end = time.time() + timeout
        while time.time() < end:
            try:
                with urllib.request.urlopen(url, timeout=2) as r:
                    r.read()
                    return
            except Exception:
                time.sleep(0.2)
        raise RuntimeError(f"server at {url} failed to start")

    def _drive(peers: list, ring: "DirectoryRing | None",
               pool) -> dict:
        ring_dict = ring.to_dict() if ring is not None else None
        out: dict = {}
        total_ops = 0
        total_s = 0.0
        for name, n in (("create", N_CREATE), ("lookup", N_LOOKUP),
                        ("list", N_LIST)):
            shards = [list(range(k, n, PROCS)) for k in range(PROCS)]
            t0 = time.perf_counter()
            errs = pool.starmap(_meta_driver_shard, [
                (pkg_root, peers, ring_dict, name, N_DIRS, shard,
                 THREADS, N_CREATE) for shard in shards])
            dt = time.perf_counter() - t0
            out[f"{name}_req_s"] = round(n / dt, 1)
            out["errors"] = out.get("errors", 0) + sum(errs)
            total_ops += n
            total_s += dt
        out["namespace_ops_s"] = round(total_ops / total_s, 1)
        return out

    def _boot(n_peers: int, base_port: int) -> tuple:
        mport = base_port
        peers = [f"127.0.0.1:{base_port + 1 + i}"
                 for i in range(n_peers)]
        procs = [subprocess.Popen(
            [sys.executable, "-m", "seaweedfs_tpu.cli", "master",
             "-ip", "127.0.0.1", "-port", str(mport)],
            env=dict(env, WEED_FILER_RING_PEERS=",".join(peers)
                     if n_peers > 1 else ""),
            cwd=work, stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL)]
        _wait_http(f"http://127.0.0.1:{mport}/cluster/status")
        for p in peers:
            port = p.rsplit(":", 1)[1]
            cmd = [sys.executable, "-m", "seaweedfs_tpu.cli", "filer",
                   "-ip", "127.0.0.1", "-port", port,
                   "-mserver", f"127.0.0.1:{mport}",
                   "-store", "memory"]
            if n_peers > 1:
                cmd += ["-ring_peers", ",".join(peers)]
            procs.append(subprocess.Popen(
                cmd,
                env=dict(env, WEED_FILER_RING_REPLICAS=str(
                    _RING_BENCH_REPLICAS),
                         WEED_FILER_RING_VNODES="256"),
                cwd=work, stdout=subprocess.DEVNULL,
                stderr=subprocess.DEVNULL))
        for p in peers:
            _wait_http(f"http://{p}/__meta__/info")
        return procs, peers, mport

    def _kill(procs) -> None:
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
        time.sleep(0.5)

    out: dict = {"driver": {"processes": PROCS, "threads": THREADS},
                 "ops": {"create": N_CREATE, "lookup": N_LOOKUP,
                         "list": N_LIST},
                 # the scaling rows run replicas=1 (pure partition
                 # scaling — the DirectoryRing's own axis); the
                 # replicated row below prices the durability knob
                 "ring_replicas": _RING_BENCH_REPLICAS}
    ctx = mp.get_context("spawn")
    with ctx.Pool(PROCS) as client_pool:
        # warm the pool (spawn + import cost must not land in a row)
        client_pool.starmap(_meta_noop, [() for _ in range(PROCS)])
        # both clusters stay up and the rows INTERLEAVE, median-of-3
        # each: this shared host drifts on the tens-of-seconds scale,
        # so back-to-back pass pairs see the same machine while
        # separated rows would eat the drift as a phantom (anti-)speedup
        procs_1, peers_1, _ = _boot(1, 21555)
        procs_3, peers_3, _ = _boot(3, 22555)
        try:
            ring = DirectoryRing(peers=peers_3, vnodes=256,
                                 replicas=_RING_BENCH_REPLICAS)
            single_rows, ring_rows = [], []
            for _ in range(3):
                single_rows.append(_drive(peers_1, None, client_pool))
                ring_rows.append(_drive(peers_3, ring, client_pool))
                _phase_checkpoint(work, "metadata", out)
                if time.time() > deadline - 30 and single_rows:
                    break
            out["single"] = sorted(
                single_rows,
                key=lambda r: r["namespace_ops_s"])[len(single_rows) // 2]
            out["ring3"] = sorted(
                ring_rows,
                key=lambda r: r["namespace_ops_s"])[len(ring_rows) // 2]
        finally:
            _kill(procs_1 + procs_3)
        _phase_checkpoint(work, "metadata", out)
        # informational: the same ring at replicas=2 (synchronous
        # successor mirrors on every write) — the price of the
        # zero-loss-on-peer-kill contract, NOT an acceptance row
        if time.time() < deadline - 45:
            saved = _RING_BENCH_REPLICAS
            _RING_BENCH_REPLICAS = 2
            try:
                procs_r, peers_r, _ = _boot(3, 23555)
                try:
                    ring_r = DirectoryRing(peers=peers_r, vnodes=256,
                                           replicas=2)
                    out["ring3_replicated"] = _drive(peers_r, ring_r,
                                                     client_pool)
                finally:
                    _kill(procs_r)
            except Exception as e:
                out["ring3_replicated"] = {"error": str(e)}
            finally:
                _RING_BENCH_REPLICAS = saved
    ratio = round(out["ring3"]["namespace_ops_s"]
                  / max(out["single"]["namespace_ops_s"], 1), 3)
    out["scaling_3p"] = ratio
    out["accept"] = {"threex_vs_single_ge_1_8": ratio >= 1.8,
                     "zero_errors": out["single"]["errors"] == 0
                     and out["ring3"]["errors"] == 0}
    _phase_checkpoint(work, "metadata", out)
    return out


def phase_recovery(work: str, budget_s: float = 240.0,
                   target_mb: int = 1024) -> dict:
    """Crash-consistency plane: cold-start recovery wall time for a
    torn ~1GB volume (the ISSUE 15 acceptance shape) plus crashsim
    sweep throughput (crash points/sec).

    The volume is built with a mid-stream sync() watermark, an un-synced
    tail, and a deliberate tear (truncate mid-record + garbage stump).
    recovery_wall_s is the watermarked open — the production cold-start
    cost; full_scan_gbps prices the legacy no-watermark CRC scan the
    same open would pay on a pre-`.swm` volume."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from seaweedfs_tpu.crashsim.harness import sweep_all
    from seaweedfs_tpu.storage.needle import Needle
    from seaweedfs_tpu.storage.volume import Volume

    t_start = time.perf_counter()
    out: dict = {"target_mb": target_mb}
    vdir = os.path.join(work, "recovery_vol")
    os.makedirs(vdir, exist_ok=True)

    # budget-aware sizing: the 1GB target needs ~30s of build headroom
    if budget_s < 120:
        target_mb = min(target_mb, 256)
        out["target_mb"] = target_mb

    payload = (b"\xa5" * 65536)
    t0 = time.perf_counter()
    v = Volume(vdir, "", 77, create=True)
    nid = 0
    target = target_mb * MB
    while v.data_file_size() < target * 0.97:
        nid += 1
        v.write_needle(Needle(cookie=0xCC, id=nid,
                              data=payload + nid.to_bytes(8, "big")))
    v.sync()
    synced_ids = nid
    wm_size = v.data_file_size()
    for _ in range(12):                       # un-synced tail
        nid += 1
        v.write_needle(Needle(cookie=0xCC, id=nid, data=payload))
    torn_size = v.data_file_size()
    v.nm.close()
    v._dat.close()
    out["build_s"] = round(time.perf_counter() - t0, 2)
    out["volume_bytes"] = torn_size
    base = v.base_file_name()
    with open(base + ".dat", "r+b") as f:     # tear the last record
        f.truncate(torn_size - 30000)
        f.seek(torn_size - 62000)
        f.write(os.urandom(4096))
    _phase_checkpoint(work, "recovery", out)

    t0 = time.perf_counter()
    v2 = Volume(vdir, "", 77)
    out["recovery_wall_s"] = round(time.perf_counter() - t0, 3)
    # the cut may keep whole un-synced tail records before the tear —
    # legal (un-acked, intact); everything acked must be byte-exact
    recovered_ok = (wm_size <= v2.data_file_size() < torn_size
                    and len(v2.nm) >= synced_ids)
    sample = {1, synced_ids // 2, synced_ids}
    for sid in sample:
        n = v2.read_needle(sid)
        recovered_ok = recovered_ok and \
            n.data == payload + sid.to_bytes(8, "big")
    out["recovered_byte_exact"] = recovered_ok
    # legacy cost: the full CRC scan a watermark-less volume would pay
    t0 = time.perf_counter()
    cut, records = v2._scan_valid_records(
        v2.super_block.block_size(), v2.data_file_size())
    full_scan_s = time.perf_counter() - t0
    out["full_scan_s"] = round(full_scan_s, 3)
    out["full_scan_gbps"] = round(
        v2.data_file_size() / max(full_scan_s, 1e-9) / 1e9, 3)
    out["full_scan_records"] = len(records)
    v2.close()
    shutil.rmtree(vdir, ignore_errors=True)
    _phase_checkpoint(work, "recovery", out)

    t0 = time.perf_counter()
    summary = sweep_all(seeds=2, points=20)
    sweep_s = time.perf_counter() - t0
    out["crashsim_points"] = summary["total_points"]
    out["crashsim_violations"] = summary["total_violations"]
    out["crashsim_points_per_s"] = round(
        summary["total_points"] / max(sweep_s, 1e-9), 1)
    out["crashsim_sweep_s"] = round(sweep_s, 2)
    out["accept"] = {
        "recovered_byte_exact": bool(recovered_ok),
        "zero_sweep_violations": summary["total_violations"] == 0,
        "sweep_points_ge_200": summary["total_points"] >= 200,
    }
    out["phase_wall_s"] = round(time.perf_counter() - t_start, 2)
    _phase_checkpoint(work, "recovery", out)
    return out


V2_RULES = ("blocking-call-transitive,lock-held-await-transitive,"
            "deadline-propagation,resource-leak-interproc,lock-ordering")


def phase_lint(work: str = "", budget_s: float = 60.0) -> dict:
    """weedlint smoke: the full-tree static-analysis gate must stay
    cheap enough to live inside the tier-1 pytest run — WITH the v2
    call-graph pass included. Runs the exact CI invocation
    (scripts/lint.sh's command line) in a subprocess and records wall
    time (lint_wall_s), then the inter-procedural subset alone
    (lint_v2_wall_s: call-graph build + summary closure cost);
    acceptance is clean exits AND full run < 10s."""
    repo = os.path.dirname(os.path.abspath(__file__))
    cmd = [sys.executable, "-m", "seaweedfs_tpu.analysis",
           "--baseline", ".weedlint-baseline.json",
           "seaweedfs_tpu/", "tests/"]
    t0 = time.perf_counter()
    p = subprocess.run(cmd, cwd=repo, capture_output=True, text=True,
                       timeout=budget_s)
    wall = time.perf_counter() - t0
    tail = (p.stdout.strip().splitlines() or [""])[-1]

    cmd_v2 = [sys.executable, "-m", "seaweedfs_tpu.analysis",
              "--rules", V2_RULES, "--baseline",
              ".weedlint-baseline.json", "seaweedfs_tpu/", "tests/"]
    t0 = time.perf_counter()
    p2 = subprocess.run(cmd_v2, cwd=repo, capture_output=True,
                        text=True, timeout=budget_s)
    wall_v2 = time.perf_counter() - t0

    out = {
        "lint_wall_s": round(wall, 2),
        "lint_v2_wall_s": round(wall_v2, 2),
        "clean": p.returncode == 0 and p2.returncode == 0,
        "files": int(tail.split(" files")[0].rsplit(" ", 1)[-1])
        if " files" in tail else None,
        "summary": tail[:200],
        "accept": {"clean_exit": p.returncode == 0,
                   "v2_clean_exit": p2.returncode == 0,
                   "under_10s": wall < 10.0},
    }
    if p.returncode != 0 or p2.returncode != 0:
        out["error"] = (p.stdout + p.stderr + p2.stdout
                        + p2.stderr)[-1500:]
    return out


def phase_scale(work: str = "", budget_s: float = 240.0) -> dict:
    """Planet-scale control plane at 1000 virtual nodes (clustersim):
    planner decision latency over a fully-registered skewed topology,
    then the scenario sweep's moved-bytes ratio / convergence /
    violation counts.  Pure CPU python — no TPU, no sockets, virtual
    clock — so the numbers are control-plane algorithm costs, not I/O.
    Checkpointed per scenario: a timeout keeps every scenario already
    measured."""
    from seaweedfs_tpu.balance.planner import plan_moves
    from seaweedfs_tpu.clustersim import scenarios
    from seaweedfs_tpu.clustersim.sim import ClusterSim

    deadline = time.perf_counter() + budget_s
    out: dict = {"nodes": 1000}

    # planner decision latency: a 1000-node topology with 3 hot nodes,
    # registered through the real heartbeat intake, planned repeatedly
    sim = ClusterSim(nodes=1000, seed=0)
    for i in range(3):
        for vid in sorted(sim.node(i).volumes):
            sim.at(1, "heat", i, vid, 2.0)
    sim.run(10)
    durs = []
    for _ in range(30):
        t0 = time.perf_counter()
        plan = plan_moves(sim.topology, sim.cfg, sim.clock.now(),
                          seed=0, frozen=frozenset())
        durs.append((time.perf_counter() - t0) * 1000.0)
    durs.sort()
    out["plan_p50_ms"] = round(durs[len(durs) // 2], 2)
    out["plan_p95_ms"] = round(durs[int(len(durs) * 0.95)], 2)
    out["plan_moves_proposed"] = len(plan)
    _phase_checkpoint(work, "scale", out)

    total_violations = 0
    for name in ("skew", "churn", "rackloss"):
        if time.perf_counter() > deadline - 30:
            out[name] = {"error": "skipped (budget)"}
            continue
        t0 = time.perf_counter()
        rep = scenarios.run_scenario(name, seed=0, nodes=1000)
        total_violations += len(rep["violations"])
        out[name] = {
            "wall_s": round(time.perf_counter() - t0, 2),
            "ticks": rep["ticks"],
            "moves": rep["moves"],
            "repairs": rep["repairs"],
            "moved_bytes_ratio": rep["moved_bytes_ratio"],
            "converge_tick": rep.get("converge_tick"),
            "violations": rep["violations"],
        }
        _phase_checkpoint(work, "scale", out)
    out["moved_bytes_ratio"] = (out.get("skew") or {}).get(
        "moved_bytes_ratio")
    out["violations_total"] = total_violations
    out["accept"] = {"zero_violations": total_violations == 0,
                     "plan_under_1s": out["plan_p50_ms"] < 1000.0}
    return out


# ------------------------------------------------------------ orchestration

def _run_phase(name: str, work: str, timeout_s: float) -> dict:
    """Run one phase in a fresh subprocess (fresh tunnel); the phase
    prints its JSON on the LAST stdout line. A phase that times out or
    dies still contributes whatever it checkpointed into
    <name>_partial.json (merged under the error record) instead of
    nulling every number it had already measured."""
    t0 = time.perf_counter()
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "SEAWEEDFS_FORCE_CPU")}
    # one persistent compilation cache shared by every phase: the
    # rebuild phase's dynamic-matrix window program IS the program the
    # encode phase compiled (ec/coder.py), so rebuild warms from the
    # encode cache even though each phase is a fresh process
    cache_dir = os.path.join(work, "jax_cache")
    os.makedirs(cache_dir, exist_ok=True)
    env.setdefault("JAX_COMPILATION_CACHE_DIR", cache_dir)
    env.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")
    try:
        p = subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--phase", name, "--work", work,
             "--budget", str(int(timeout_s * 0.9))],
            capture_output=True, text=True, timeout=timeout_s, env=env,
            cwd=os.path.dirname(os.path.abspath(__file__)))
    except subprocess.TimeoutExpired:
        return {"error": f"phase {name} timed out after {timeout_s:.0f}s",
                **_load_partial(work, name)}
    dur = time.perf_counter() - t0
    if p.returncode != 0:
        tail = (p.stderr or "")[-2000:]
        return {"error": f"phase {name} rc={p.returncode}: {tail}",
                **_load_partial(work, name)}
    try:
        out = json.loads(p.stdout.strip().splitlines()[-1])
    except Exception as e:
        return {"error": f"phase {name} bad output: {e}; "
                         f"stdout tail: {p.stdout[-500:]}",
                **_load_partial(work, name)}
    out["phase_wall_s"] = round(dur, 1)
    return out


def _log(msg: str) -> None:
    print(f"[bench] {msg}", file=sys.stderr, flush=True)


DETAIL_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "BENCH_DETAIL.json")


def _checkpoint(detail: dict, path: str = "") -> None:
    """Write the (partial) detail record NOW, atomically. Each phase
    checkpoints as it completes, so a later phase timing out — or the
    whole run being killed — no longer nulls every earlier number."""
    path = path or DETAIL_PATH
    try:
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(detail, f, indent=1)
        os.replace(tmp, path)
    except OSError as e:
        _log(f"checkpoint write failed: {e}")


def main() -> None:
    started = time.perf_counter()
    work = tempfile.mkdtemp(prefix="swfs_bench_")

    def left() -> float:
        return HARD_BUDGET_S - (time.perf_counter() - started)

    try:
        # host-side prep (parent NEVER touches the TPU: jax stays
        # un-imported here so subprocess tunnels start clean)
        t0 = time.perf_counter()
        _make_volume(os.path.join(work, "1.dat"), VOL_BYTES)
        _log(f"volume gen: {time.perf_counter() - t0:.1f}s")

        # per-phase incremental record: every phase lands in
        # BENCH_DETAIL.json the moment it completes
        detail = {"volume_bytes": VOL_BYTES, "incomplete": True}

        # the one-time program load alone varies 40-280s through the
        # tunnel; 300s was measured to clip real runs
        encode = _run_phase("encode", work, min(430.0, left()))
        _log(f"encode: {encode.get('value_gbps')} GB/s "
             f"({encode.get('phase_wall_s')}s)")
        detail["encode"] = encode
        _checkpoint(detail)

        # kernel before rebuild: its per-config compiles are the
        # predictable TPU work (~340s total), while the rec-window
        # compile+load has measured anywhere from 140 to 540+s — the
        # unpredictable phase runs LAST among the TPU phases and gets
        # all the remaining TPU budget
        kernel = _run_phase("kernel", work, min(420.0, max(left(), 60)))
        _log(f"kernel: {kernel.get('kernel', {}).get('gbps')} GB/s "
             f"({kernel.get('phase_wall_s')}s)")
        detail["kernel_phase"] = kernel
        _checkpoint(detail)

        # shard files for the rebuild phase (host coder, parent-side)
        rebuild: dict = {"error": "skipped (budget)"}
        if left() > 200:
            t0 = time.perf_counter()
            sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
            os.environ.setdefault("JAX_PLATFORMS", "cpu")
            from seaweedfs_tpu.ec import pipeline as _pl
            _pl.stream_encode(os.path.join(work, "1"), _host_coder(),
                              batch_size=BATCH_W)
            _log(f"shard gen (host): {time.perf_counter() - t0:.1f}s")
            # leave ~180s for fused+system+needle_map after rebuild
            rebuild = _run_phase("rebuild", work,
                                 min(650.0, max(left() - 180.0, 60.0)))
        if rebuild.get("rebuild_p50_s") is None:
            # a skipped/unreached phase has no p50: print its reason
            # instead of the literal "p50 Nones (Nones)" (BENCH_r05
            # tail); the JSON keeps the "skipped: ..." string as-is
            reason = str(rebuild.get("error", "not reached"))
            if not reason.startswith("skipped"):
                reason = f"skipped ({reason})"
            _log(f"rebuild: {reason}")
        else:
            _log(f"rebuild: p50 {rebuild.get('rebuild_p50_s')}s "
                 f"({rebuild.get('phase_wall_s')}s)")
        detail["rebuild"] = rebuild
        _checkpoint(detail)

        fused = ({"error": "skipped (budget)"} if left() < 120
                 else _run_phase("fused", work, min(240.0, left())))
        _log(f"fused: {fused.get('gbps')} GB/s steady "
             f"({fused.get('speedup')}x chained, "
             f"scrub redigests {fused.get('scrub_redigests')})")
        detail["fused_compact_gzip_rs"] = fused
        _checkpoint(detail)

        try:
            system = bench_system(work)
            _log(f"system: w {system['write']['req_s']} r "
                 f"{system['read']['req_s']}")
            sh = system.get("sharded")
            if isinstance(sh, dict) and "write_req_s" in sh:
                _log(f"system (sharded x{sh['shards']}): "
                     f"w {sh['write_req_s']} r {sh['read_req_s']}")
            elif isinstance(sh, str):
                _log(f"system (sharded): {sh}")
        except Exception as e:
            system = {"error": str(e)}
        detail["system_req_s"] = system
        _checkpoint(detail)

        saturation: dict = {"error": "skipped (budget)"}
        if left() > 150:
            try:
                saturation = phase_saturation(
                    work, budget_s=min(240.0, left() - 90.0))
                _log(f"saturation: {saturation.get('host_cores')} cores,"
                     f" shards={saturation.get('shards')}, speedup "
                     f"{saturation.get('speedup')}")
            except Exception as e:
                saturation = {"error": str(e)}
        detail["saturation"] = saturation
        _checkpoint(detail)

        largefile: dict = {"error": "skipped (budget)"}
        if left() > 90:
            try:
                largefile = phase_largefile(work)
                _log(f"largefile: PUT {largefile.get('put_mb_s')} MB/s "
                     f"GET {largefile.get('get_mb_s')} MB/s")
            except Exception as e:
                largefile = {"error": str(e),
                             **_load_partial(work, "largefile")}
        detail["largefile_mb_s"] = largefile
        _checkpoint(detail)

        degraded: dict = {"error": "skipped (budget)"}
        if left() > 120:
            try:
                degraded = phase_degraded(
                    work, budget_s=min(240.0, left() - 60.0))
                _log(f"degraded: p50 {degraded.get('degraded_p50_ms')}ms "
                     f"p99 {degraded.get('degraded_p99_ms')}ms")
            except Exception as e:
                degraded = {"error": str(e), **_load_partial(work,
                                                             "degraded")}
        detail["degraded_read"] = degraded
        _checkpoint(detail)

        overload: dict = {"error": "skipped (budget)"}
        if left() > 80:
            try:
                overload = phase_overload(
                    work, budget_s=min(150.0, left() - 30.0))
                _log(f"overload: peak "
                     f"{(overload.get('peak') or {}).get('goodput_req_s')}"
                     f" req/s, 3x-offered goodput ratio "
                     f"{overload.get('goodput_ratio')}")
            except Exception as e:
                overload = {"error": str(e), **_load_partial(work,
                                                             "overload")}
        detail["overload"] = overload
        _checkpoint(detail)

        lifecycle: dict = {"error": "skipped (budget)"}
        if left() > 100:
            try:
                lifecycle = phase_lifecycle(
                    work, budget_s=min(240.0, left() - 30.0))
                _log(f"lifecycle: {lifecycle.get('warmed_volumes')} "
                     f"warmed, batch {lifecycle.get('time_to_warm_all_s')}"
                     f"s, hot p50 ratio {lifecycle.get('hot_p50_ratio')}")
            except Exception as e:
                lifecycle = {"error": str(e),
                             **_load_partial(work, "lifecycle")}
        detail["lifecycle"] = lifecycle
        _checkpoint(detail)

        georepl: dict = {"error": "skipped (budget)"}
        if left() > 90:
            try:
                georepl = phase_georepl(
                    work, budget_s=min(240.0, left() - 30.0))
                _log(f"georepl: steady lag "
                     f"{(georepl.get('steady_lag_s') or {}).get('median')}s, "
                     f"storm ratio {georepl.get('lag_ratio')}")
            except Exception as e:
                georepl = {"error": str(e),
                           **_load_partial(work, "georepl")}
        detail["georepl"] = georepl
        _checkpoint(detail)

        # multichip runs in its own subprocess because it must pin
        # JAX_PLATFORMS=cpu + the 8-virtual-device flag BEFORE jax
        # initializes (the phase body sets both; a TPU-attached parent
        # env would otherwise grab the tunnel)
        multichip: dict = {"error": "skipped (budget)"}
        if left() > 90:
            multichip = _run_phase("multichip", work, min(260.0, left()))
            sc = multichip.get("scaling") or {}
            _log(f"multichip: projected "
                 f"{[(n, (v.get('aggregate_projected_gbps') if isinstance(v, dict) else v)) for n, v in sorted(sc.items())]}, "
                 f"storm ratio "
                 f"{(multichip.get('rebuild_storm') or {}).get('drain_ratio') if isinstance(multichip.get('rebuild_storm'), dict) else None}")
        detail["multichip"] = multichip
        _checkpoint(detail)

        metadata: dict = {"error": "skipped (budget)"}
        if left() > 90:
            try:
                metadata = phase_metadata(
                    work, budget_s=min(240.0, left() - 30.0))
                _log(f"metadata: single "
                     f"{(metadata.get('single') or {}).get('namespace_ops_s')}"
                     f" ops/s, 3-peer ring x{metadata.get('scaling_3p')}")
            except Exception as e:
                metadata = {"error": str(e),
                            **_load_partial(work, "metadata")}
        detail["metadata"] = metadata
        _checkpoint(detail)

        observe_res: dict = {"error": "skipped (budget)"}
        if left() > 90:
            try:
                observe_res = phase_observe(
                    work, budget_s=min(150.0, left() - 60.0))
                _log(f"observe: p50 off {observe_res['off']['p50_ms']}ms "
                     f"armed {observe_res['armed']['p50_ms']}ms "
                     f"({observe_res['p50_regression_pct']}%)")
            except Exception as e:
                observe_res = {"error": str(e),
                               **_load_partial(work, "observe")}
        detail["observe"] = observe_res
        _checkpoint(detail)

        try:
            lint = phase_lint(work)
            _log(f"lint: {lint.get('lint_wall_s')}s over "
                 f"{lint.get('files')} files, clean={lint.get('clean')}")
        except Exception as e:
            lint = {"error": str(e)}
        detail["lint"] = lint
        _checkpoint(detail)

        scale: dict = {"error": "skipped (budget)"}
        if left() > 60:
            try:
                scale = phase_scale(work, budget_s=min(180.0, left() - 30.0))
                _log(f"scale: 1000-node plan p50 "
                     f"{scale.get('plan_p50_ms')}ms, skew moved-bytes "
                     f"ratio {scale.get('moved_bytes_ratio')}, "
                     f"{scale.get('violations_total')} violations")
            except Exception as e:
                scale = {"error": str(e), **_load_partial(work, "scale")}
        detail["scale"] = scale
        _checkpoint(detail)

        recovery: dict = {"error": "skipped (budget)"}
        if left() > 60:
            try:
                recovery = phase_recovery(
                    work, budget_s=min(240.0, left() - 20.0))
                _log(f"recovery: torn-{recovery.get('target_mb')}MB "
                     f"cold start {recovery.get('recovery_wall_s')}s, "
                     f"crashsim {recovery.get('crashsim_points')} pts @ "
                     f"{recovery.get('crashsim_points_per_s')}/s, "
                     f"{recovery.get('crashsim_violations')} violations")
            except Exception as e:
                recovery = {"error": str(e),
                            **_load_partial(work, "recovery")}
        detail["recovery"] = recovery
        _checkpoint(detail)

        try:
            needle_map = bench_needle_map(work)
        except Exception as e:
            needle_map = {"error": str(e)}
        detail["disk_needle_map"] = needle_map

        value = encode.get("value_gbps") or 0.0
        detail.pop("incomplete", None)
        detail.update({
            "note": (
                "value = steady-state per-volume pipeline rate "
                "(read+stage+execute, program already loaded, window "
                "dispatches pipelined — the 1000-volume regime of "
                "BASELINE config 2). Each TPU phase runs in a fresh "
                "process because the tunneled dev link degrades ~100x "
                "after any D2H read; cold_pass_s includes the one-time "
                "program load. Digests verified against an independent "
                "host coder in every phase. The stage rate trails the "
                "isolated H2D link rate because the disk reader and the "
                "device_put copy contend for this host's ONE core "
                "(probed: [10,16M] puts alone run at full link rate); "
                "host-side feed rates are host properties — the "
                "chip-side rates are chip_encode_gbps / "
                "rebuild_window_gbps."),
        })
        # final full record; stdout's LAST line stays small and
        # single-line so the driver's parse cannot truncate it
        _checkpoint(detail)
        enc_rates = encode.get("component_rates_gbps") or {}
        print(json.dumps({
            "metric": ("ec.encode pipeline GB/s/chip (disk -> H2D -> "
                       "kernel, device parity sink, steady state, "
                       "tunneled dev link)"),
            "value": value,
            "unit": "GB/s",
            "vs_baseline": round(value / BASELINE_GBPS, 3),
            "extra": {
                "chip_encode_gbps": encode.get("chip_encode_gbps"),
                "encode_feed_stages_s": encode.get("feed_stages_s"),
                "healthy_link_projection_gbps":
                    encode.get("healthy_link_projection_gbps"),
                "healthy_link_binding_stage":
                    encode.get("healthy_link_binding_stage"),
                "kernel_window_gbps": enc_rates.get("kernel_window"),
                "pinned_kernel_gbps":
                    (kernel.get("kernel") or {}).get("gbps"),
                "sweep_kernel_gbps": kernel.get("sweep_kernel_gbps"),
                "tile_sweep_gbps": kernel.get("tile_sweep_gbps"),
                "rebuild_p50_s": rebuild.get("rebuild_p50_s"),
                "rebuild_window_gbps":
                    rebuild.get("rebuild_window_gbps"),
                "rebuild_batch_steady_per_volume_s":
                    ((rebuild.get("rebuild_batch") or {})
                     .get("amortization_model")
                     or {}).get("steady_per_volume_s"),
                "system_write_req_s":
                    (system.get("write") or {}).get("req_s")
                    if isinstance(system.get("write"), dict) else None,
                "system_read_req_s":
                    (system.get("read") or {}).get("req_s")
                    if isinstance(system.get("read"), dict) else None,
                "largefile_put_mb_s": largefile.get("put_mb_s"),
                "largefile_get_mb_s": largefile.get("get_mb_s"),
                "degraded_read_p50_ms": degraded.get("degraded_p50_ms"),
                "degraded_read_p99_ms": degraded.get("degraded_p99_ms"),
                "overload_goodput_ratio": overload.get("goodput_ratio"),
                "overload_p99_ms":
                    (overload.get("overload") or {}).get("p99_ms"),
                "observe_p50_regression_pct":
                    observe_res.get("p50_regression_pct"),
                "lifecycle_time_to_warm_s":
                    lifecycle.get("time_to_warm_all_s"),
                "lifecycle_hot_p50_ratio":
                    lifecycle.get("hot_p50_ratio"),
                "georepl_steady_lag_s":
                    (georepl.get("steady_lag_s") or {}).get("median"),
                "georepl_lag_ratio": georepl.get("lag_ratio"),
                "metadata_single_ops_s":
                    (metadata.get("single") or {}).get(
                        "namespace_ops_s"),
                "metadata_ring3_ops_s":
                    (metadata.get("ring3") or {}).get(
                        "namespace_ops_s"),
                "metadata_scaling_3p": metadata.get("scaling_3p"),
                "multichip_scaling": multichip.get("scaling"),
                "multichip_storm_drain_ratio":
                    (multichip.get("rebuild_storm") or {}).get(
                        "drain_ratio")
                    if isinstance(multichip.get("rebuild_storm"), dict)
                    else None,
                "fused_gbps": fused.get("gbps"),
                "fused_speedup_vs_chained": fused.get("speedup"),
                "fused_scrub_redigests": fused.get("scrub_redigests"),
                "lint_wall_s": lint.get("lint_wall_s"),
                "lint_v2_wall_s": lint.get("lint_v2_wall_s"),
                "recovery_wall_s": recovery.get("recovery_wall_s"),
                "recovery_full_scan_gbps":
                    recovery.get("full_scan_gbps"),
                "crashsim_points_per_s":
                    recovery.get("crashsim_points_per_s"),
                "scale_plan_p50_ms": scale.get("plan_p50_ms"),
                "scale_moved_bytes_ratio":
                    scale.get("moved_bytes_ratio"),
                "scale_violations": scale.get("violations_total"),
                "detail_file": "BENCH_DETAIL.json",
            },
        }))
    finally:
        shutil.rmtree(work, ignore_errors=True)


if __name__ == "__main__":
    if "--phase" in sys.argv:
        name = sys.argv[sys.argv.index("--phase") + 1]
        work = sys.argv[sys.argv.index("--work") + 1]
        budget = (float(sys.argv[sys.argv.index("--budget") + 1])
                  if "--budget" in sys.argv else 580.0)
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        fn = {"encode": phase_encode,
              "rebuild": lambda w: phase_rebuild(w, budget_s=budget),
              "kernel": lambda w: phase_kernel(w, budget_s=budget),
              "fused": lambda w: phase_fused(w, budget_s=budget),
              "multichip": lambda w: phase_multichip(w, budget_s=budget),
              "degraded": lambda w: phase_degraded(w, budget_s=budget),
              "largefile": phase_largefile,
              "overload": lambda w: phase_overload(w, budget_s=budget),
              "observe": lambda w: phase_observe(w, budget_s=budget),
              "lifecycle": lambda w: phase_lifecycle(w, budget_s=budget),
              "georepl": lambda w: phase_georepl(w, budget_s=budget),
              "metadata": lambda w: phase_metadata(w, budget_s=budget),
              "lint": lambda w: phase_lint(w, budget_s=budget),
              "scale": lambda w: phase_scale(w, budget_s=budget),
              "recovery": lambda w: phase_recovery(w, budget_s=budget),
              }[name]
        print(json.dumps(fn(work)))
    else:
        main()
