#!/usr/bin/env python3
"""Headline benchmark: RS(10,4) ec.encode throughput on one chip.

Mirrors BASELINE config 2 (batched volumes, 1MB-block stripes -> TPU): feeds
the fused Pallas GF(2^8) kernel 640MB data batches ([10 x 64MiB] stripes,
i.e. the coder-visible shape of the reference encode loop
weed/storage/erasure_coding/ec_encoder.go:162-192) and reports steady-state
data throughput. Baseline for vs_baseline is the BASELINE.json north-star
target of 20 GB/s/chip.

Prints exactly one JSON line:
  {"metric": ..., "value": N, "unit": "GB/s", "vs_baseline": N}
"""

import json
import sys
import time

import numpy as np

BASELINE_GBPS = 20.0  # BASELINE.json: ec.encode >= 20 GB/s/chip on v5e


def main() -> None:
    import jax
    import jax.numpy as jnp

    from seaweedfs_tpu.ops import gf256, rs_pallas

    backend = jax.default_backend()
    n = 64 * 1024 * 1024 if backend == "tpu" else 1024 * 1024
    data = jnp.asarray(
        np.random.default_rng(0).integers(0, 256, (10, n), dtype=np.uint8))

    fn = rs_pallas.gf_apply_pallas(gf256.parity_matrix(10, 4))
    out = fn(data)
    out.block_until_ready()  # compile + warm

    # correctness gate: never report speed for wrong parity
    check = np.asarray(out[:, :65536])
    want = gf256.encode_parity(np.asarray(data[:, :65536]), 4)
    if not np.array_equal(check, want):
        print(json.dumps({"metric": "ec.encode GB/s/chip", "value": 0.0,
                          "unit": "GB/s", "vs_baseline": 0.0,
                          "error": "parity mismatch"}))
        sys.exit(1)

    reps = 10 if backend == "tpu" else 3
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(data)
    out.block_until_ready()
    dt = (time.perf_counter() - t0) / reps

    gbps = (10 * n) / dt / 1e9
    print(json.dumps({
        "metric": "ec.encode GB/s/chip",
        "value": round(gbps, 2),
        "unit": "GB/s",
        "vs_baseline": round(gbps / BASELINE_GBPS, 3),
    }))


if __name__ == "__main__":
    main()
