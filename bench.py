#!/usr/bin/env python3
"""Headline benchmark: the RS(10,4) ec.encode PIPELINE on one chip.

Round-1 benched only the kernel on pre-staged HBM arrays; the north star
(BASELINE config 1/2) is the full `.dat` -> `.ec00-13` encode path the
servers actually run. This bench measures, in order:

  pipeline   stream_encode of a >=1GB synthetic volume at the reference
             geometry (1MB small-block stripes for a 1GB volume — the exact
             layout ec_encoder.go:194-231 produces), overlapped disk read /
             host->HBM / Pallas kernel / 14-way shard write-back
             (seaweedfs_tpu/ec/pipeline.py). Measured twice: once writing
             the shard files (the production path; D2H-link-bound on
             tunneled dev chips) and once with the parity landing in an
             on-device digest sink (the headline: the pipeline's worth
             independent of a degraded D2H link, digest-verified against
             the shard files so it provably runs the same computation).
  kernel     the fused Pallas GF(2^8) kernel on resident data (the on-TPU
             portion; BASELINE target >=20 GB/s/chip) — pinned n/reps,
             median of 3 rounds with spread, plus a tile sweep
  rebuild    stream_rebuild of 4 missing shards from 10 survivors, p50 over
             repetitions (BASELINE config 3)
  sweep      kernel encode GB/s at RS(6,3)/(12,4)/(20,4) (BASELINE config 4)

Prints exactly one JSON line:
  {"metric": ..., "value": N, "unit": "GB/s", "vs_baseline": N, "extra": {...}}
vs_baseline is pipeline GB/s over the 20 GB/s/chip north-star target.
"""

import json
import os
import shutil
import statistics
import sys
import tempfile
import time

import numpy as np

BASELINE_GBPS = 20.0  # BASELINE.json: ec.encode >= 20 GB/s/chip on v5e

# time budgets for the degraded-tunnel case. HARD_BUDGET_S bounds the
# whole run: every optional phase carries a cost estimate (seeded by the
# measured durations of earlier phases — remote kernel compiles on a
# tunneled chip range 30-600s) and is skipped, type-stably, when it would
# blow the budget. REBUILD_BUDGET_S bounds the rebuild rep loop within
# the disk phase.
HARD_BUDGET_S = 1000.0
REBUILD_BUDGET_S = 300.0
# disk-mode encode + rebuild must cross the D2H link; they are skipped when
# the measured link predicts they'd blow the budget
DISK_DEADLINE_S = 600.0


def _make_volume(path: str, size: int) -> None:
    rng = np.random.default_rng(7)
    with open(path, "wb") as f:
        left = size
        while left > 0:
            n = min(left, 64 * 1024 * 1024)
            f.write(rng.integers(0, 256, n, dtype=np.uint8).tobytes())
            left -= n


def measure_link() -> tuple[float, float, float]:
    """Host<->device link: (h2d GB/s, d2h GB/s, d2h per-op latency s).

    On tunneled single-chip dev environments (axon) the device->host
    direction can be orders of magnitude slower than HBM AND carries a
    multi-second per-operation latency — a 16-byte fetch costs the same
    seconds as a 1MB one. Both numbers are measured so the bench can model
    a D2H-crossing phase as ops*latency + bytes/bandwidth."""
    import jax
    x = np.zeros(64 * 1024 * 1024, dtype=np.uint8)
    d = jax.device_put(x)
    d.block_until_ready()
    t0 = time.perf_counter()
    d = jax.device_put(x)
    d.block_until_ready()
    h2d = x.nbytes / (time.perf_counter() - t0) / 1e9
    tiny = jax.device_put(np.zeros(16, dtype=np.uint8))
    tiny.block_until_ready()
    np.asarray(tiny)  # first fetch may include warmup
    tiny2 = jax.device_put(np.ones(16, dtype=np.uint8))
    tiny2.block_until_ready()
    t0 = time.perf_counter()
    np.asarray(tiny2)
    d2h_lat = time.perf_counter() - t0
    e = jax.device_put(np.ones_like(x))
    e.block_until_ready()
    t0 = time.perf_counter()
    np.asarray(e)
    d2h = x.nbytes / max(time.perf_counter() - t0 - d2h_lat, 1e-9) / 1e9
    return h2d, d2h, d2h_lat


def bench_fused(work: str, coder, vol_size: int) -> dict:
    """BASELINE config 5: compaction + gzip + RS(10,4) in one pass over a
    needle volume that is ~50% garbage."""
    from seaweedfs_tpu.ec.fused import fused_vacuum_gzip_encode
    from seaweedfs_tpu.storage.needle import Needle
    from seaweedfs_tpu.storage.volume import Volume

    vdir = os.path.join(work, "fusedvol")
    os.makedirs(vdir, exist_ok=True)
    v = Volume(vdir, "", 7, create=True)
    needle_data = (b"fused bench payload: compressible text block. " * 450)
    target = min(vol_size // 8, 64 * 1024 * 1024)
    count = max(target // len(needle_data), 10)
    for i in range(1, count + 1):
        v.write_needle(Needle(cookie=i, id=i, data=needle_data))
    for i in range(1, count + 1, 2):
        v.delete_needle(Needle(cookie=i, id=i))
    src_bytes = v.data_file_size()
    dst = os.path.join(vdir, "out_7")
    t0 = time.perf_counter()
    out = fused_vacuum_gzip_encode(v, dst, coder)
    dt = time.perf_counter() - t0
    v.close()
    return {"src_bytes": src_bytes,
            "compacted_bytes": out["compacted_bytes"],
            "gbps": round(src_bytes / dt / 1e9, 3)}


def bench_kernel(k: int, m: int, n: int, reps: int, tile: int | None = None,
                 rounds: int = 1):
    """Pinned kernel measurement: fixed n, fixed reps, one warm+correctness
    pass, then `rounds` independent timed rounds of `reps` dispatches each.
    Returns (median GB/s, spread fraction across rounds) — the spread is
    what separates a code regression from tunneled-dev-chip variance."""
    import jax
    import jax.numpy as jnp
    from seaweedfs_tpu.ops import gf256, rs_jax, rs_pallas

    data = jnp.asarray(
        np.random.default_rng(0).integers(0, 256, (k, n), dtype=np.uint8))
    if jax.default_backend() == "tpu":
        fn = rs_pallas.gf_apply_pallas(
            gf256.parity_matrix(k, m), tile=tile or rs_pallas.DEFAULT_TILE)
    else:
        # pallas interpret mode is a pure-python emulator — useless for
        # timing; the XLA bitplane path is the honest CPU kernel
        fn = jax.jit(rs_jax.gf_apply_bitplane(gf256.parity_matrix(k, m)))
    out = fn(data)
    out.block_until_ready()  # compile + warm

    # correctness gate: never report speed for wrong parity
    check = np.asarray(out[:, :65536])
    want = gf256.encode_parity(np.asarray(data[:, :65536]), m)
    if not np.array_equal(check, want):
        raise AssertionError(f"parity mismatch at RS({k},{m})")

    samples = []
    for _ in range(rounds):
        t0 = time.perf_counter()
        for _ in range(reps):
            out = fn(data)
        out.block_until_ready()
        samples.append((k * n) / ((time.perf_counter() - t0) / reps) / 1e9)
    med = statistics.median(samples)
    spread = (max(samples) - min(samples)) / med if med else 0.0
    return med, spread


def bench_system(work: str, n: int = 6000, size: int = 1024,
                 concurrency: int = 16) -> dict:
    """System req/s vs the reference's published benchmark (README.md:504-553:
    15,708 writes/s, 47,019 reads/s at 1KB, c=16 — measured on multi-core
    bare metal with a Go client). Spawns the combined master+volume server
    as a subprocess and drives it with the raw-socket self-validating
    engine; numbers include the client's CPU share of the same host, so
    cpu_count is reported alongside."""
    import subprocess
    import urllib.request

    from seaweedfs_tpu.utils.bench_client import run_benchmark

    mport, vport = 19555, 18555
    data_dir = os.path.join(work, "sysbench")
    os.makedirs(data_dir, exist_ok=True)
    import seaweedfs_tpu
    pkg_root = os.path.dirname(os.path.dirname(seaweedfs_tpu.__file__))
    # servers never need a TPU (JAX_PLATFORMS alone is overridden by the
    # axon site hook; SEAWEEDFS_FORCE_CPU is honored by the CLI)
    env = dict(os.environ, JAX_PLATFORMS="cpu", SEAWEEDFS_FORCE_CPU="1")
    env["PYTHONPATH"] = pkg_root + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.Popen(
        [sys.executable, "-m", "seaweedfs_tpu.cli", "server",
         "-ip", "127.0.0.1", "-master_port", str(mport),
         "-port", str(vport), "-dir", data_dir],
        cwd=data_dir, env=env,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    try:
        deadline = time.time() + 30
        while True:  # ready = an assign that actually returns a fid
            try:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{mport}/dir/assign",
                        timeout=2) as r:
                    if "fid" in json.loads(r.read()):
                        break
            except Exception:
                pass
            if time.time() > deadline:
                raise RuntimeError("combined server failed to start")
            time.sleep(0.3)
        out = run_benchmark(f"127.0.0.1:{mport}", n=n, size=size,
                            concurrency=concurrency)
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()
    out["cpu_count"] = os.cpu_count()
    out["vs_reference"] = {
        "ref_write_req_s": 15708, "ref_read_req_s": 47019,
        "write_ratio": round(out["write"]["req_s"] / 15708, 4),
        "read_ratio": round(out["read"]["req_s"] / 47019, 4),
    }
    return out


def bench_needle_map(work: str, n: int = 5_000_000) -> dict:
    """Disk-backed needle map at volume scale: cold .sdx build from the
    .idx journal, warm adoption, and random lookup latency — the numbers
    behind the -index leveldb kinds (needle_map_leveldb.go's role)."""
    import numpy as np

    from seaweedfs_tpu.storage.needle_map import DiskNeedleMap

    rec = np.empty(n, dtype=[("k", ">u8"), ("o", ">u4"), ("s", ">u4")])
    rec["k"] = np.arange(1, n + 1)
    rec["o"] = np.arange(1, n + 1)
    rec["s"] = 1000
    path = os.path.join(work, "nmbench.idx")
    rec.tofile(path)
    del rec
    t0 = time.perf_counter()
    nm = DiskNeedleMap(path)
    cold_s = time.perf_counter() - t0
    nm.close()
    t0 = time.perf_counter()
    nm = DiskNeedleMap(path)
    warm_s = time.perf_counter() - t0
    rng = np.random.default_rng(3)
    keys = rng.integers(1, n + 1, 2000)
    lat = []
    for key in keys:
        t0 = time.perf_counter()
        nm.get(int(key))
        lat.append(time.perf_counter() - t0)
    nm.close()
    lat.sort()
    return {"entries": n, "cold_build_s": round(cold_s, 3),
            "warm_open_s": round(warm_s, 4),
            "lookup_p50_us": round(lat[len(lat) // 2] * 1e6, 1),
            "lookup_p99_us": round(lat[int(len(lat) * 0.99)] * 1e6, 1)}


def main() -> None:
    import jax

    from seaweedfs_tpu import ec
    from seaweedfs_tpu.ec import pipeline

    backend = jax.default_backend()
    on_tpu = backend == "tpu"
    # CPU fallback keeps the bench runnable in dev; the recorded numbers
    # come from the driver's TPU run. The TPU volume size is picked so the
    # shard size is an exact multiple of the batch width: a single kernel
    # shape compiles once (1120MiB -> 112 small rows -> 112MiB shards =
    # 7 x 16MiB batches).
    vol_size = (1120 * 1024 * 1024) if on_tpu else (16 * 1024 * 1024)
    kernel_n = (64 * 1024 * 1024) if on_tpu else (1024 * 1024)
    kernel_reps = 10 if on_tpu else 3
    rebuild_reps = 2 if on_tpu else 1
    # tunneled dev chips charge ~a second of round-trip latency per
    # host<->device op pair; 112MB batches keep the pipeline at 10 ops per
    # volume instead of 70 (a real PCIe host would prefer smaller batches
    # for deeper overlap — the batch width changes nothing semantically)
    batch = 112 * 1024 * 1024 if on_tpu else 1024 * 1024

    h2d_gbps, d2h_gbps, d2h_lat_s = measure_link()
    if on_tpu:
        coder = ec.get_coder("pallas", 10, 4)
    else:
        try:
            coder = ec.get_coder("cpp", 10, 4)
        except Exception:
            coder = ec.get_coder("jax", 10, 4)
    work = tempfile.mkdtemp(prefix="swfs_bench_")
    try:
        _run_configs(work, coder, vol_size, kernel_n, kernel_reps,
                     rebuild_reps, batch, backend, h2d_gbps,
                     d2h_gbps, d2h_lat_s)
    except Exception as e:
        # keep the one-JSON-line contract even for correctness failures
        print(json.dumps({
            "metric": ("ec.encode pipeline GB/s/chip "
                       "(disk -> H2D -> kernel, device parity sink)"),
            "value": 0.0, "unit": "GB/s", "vs_baseline": 0.0,
            "error": f"{type(e).__name__}: {e}"}))
        sys.exit(1)
    finally:
        shutil.rmtree(work, ignore_errors=True)


def _phase(name: str, t0: float) -> float:
    now = time.perf_counter()
    print(f"[bench] {name}: {now - t0:.1f}s", file=sys.stderr, flush=True)
    return now


def _run_configs(work, coder, vol_size, kernel_n, kernel_reps, rebuild_reps,
                 batch, backend, h2d_gbps, d2h_gbps,
                 d2h_lat_s) -> None:
    from seaweedfs_tpu import ec
    from seaweedfs_tpu.ec import pipeline

    started = time.perf_counter()
    t = started
    base = os.path.join(work, "1")
    _make_volume(base + ".dat", vol_size)
    t = _phase("volume gen", t)

    # Phase order puts the link-independent essentials (device-sink
    # pipeline, pinned kernel, system req/s) before anything that must move
    # parity across the device->host link: on tunneled dev chips that link
    # has been observed 1000x degraded, and a single disk-mode encode can
    # eat the entire driver patience (511s measured once).

    # host-side ground truth for the device sink: the same streaming
    # schedule with the host table coder, producing the [m] uint32 digest
    # the TPU sink must match (independent implementation, same fixture-
    # verified RS math)
    try:
        host_coder = ec.get_coder("cpp", 10, 4)
    except Exception:
        host_coder = ec.get_coder("numpy", 10, 4)
    want_digest = pipeline.stream_encode_device_sink(
        base, host_coder, batch_size=batch)
    t = _phase("host digest (ground truth)", t)

    # device-sink pipeline: disk read + H2D + kernel overlapped; parity is
    # reduced on-device, 16 bytes return per batch. Headline metric.
    pipeline.stream_encode_device_sink(base, coder, batch_size=batch)
    t = _phase("device-sink warm (compile)", t)
    t0 = time.perf_counter()
    sink_digest = pipeline.stream_encode_device_sink(base, coder,
                                                     batch_size=batch)
    sink_dt = time.perf_counter() - t0
    sink_gbps = vol_size / sink_dt / 1e9
    if sink_digest.tolist() != want_digest.tolist():
        raise AssertionError(
            f"device-sink digest {sink_digest} != host {want_digest}")
    t = _phase("encode timed (device sink)", t)

    # pinned headline kernel: fixed n, fixed reps, 3 timed rounds; median +
    # spread. Round 2's 41.4 -> 33.6 GB/s "regression" at RS(10,4) was
    # un-diagnosable because neither warm-state nor variance was pinned; a
    # fixed-shape tile sweep on the same warm chip showed 256K >= 128K >>
    # 64K (45.9/45.7/35.8 GB/s), i.e. the 256K tile was not the cause —
    # the spread number now quantifies the chip/tunnel variance instead.
    kernel_gbps, kernel_spread = bench_kernel(10, 4, kernel_n, kernel_reps,
                                              rounds=3)
    t = _phase("kernel 10,4 pinned", t)

    try:
        system = bench_system(work)
        t = _phase("system req/s", t)
    except Exception as e:
        system = {"error": str(e)}

    try:
        needle_map = bench_needle_map(work)
        t = _phase("disk needle map", t)
    except Exception as e:
        needle_map = {"error": str(e)}

    # adaptive estimates: a kernel phase costs roughly what the last one
    # did (compile dominates; the tunnel's remote compiler is the wild
    # card), floored at 45s
    last_kernel_s = [45.0]

    def budget_ok(est: float) -> bool:
        return time.perf_counter() - started + est < HARD_BUDGET_S

    tile_sweep = {}
    from seaweedfs_tpu.ops import rs_pallas
    for tl in (65536, 131072, rs_pallas.DEFAULT_TILE):
        if tl in tile_sweep:
            continue
        if not budget_ok(last_kernel_s[0] * 1.5):
            tile_sweep[tl] = None
            continue
        t0 = time.perf_counter()
        g, _ = bench_kernel(10, 4, kernel_n, kernel_reps, tile=tl)
        last_kernel_s[0] = max(45.0, time.perf_counter() - t0)
        tile_sweep[tl] = round(g, 2)
        t = _phase(f"kernel tile {tl}", t)

    sweep = {}
    for (k, m) in ((6, 3), (12, 4), (20, 4)):
        if not budget_ok(last_kernel_s[0] * 2):
            sweep[f"{k},{m}"] = None  # skipped (time budget); type-stable
            continue
        n = kernel_n - kernel_n % (16384 * 8)
        # measured: geometry-scaled (wider) tiles are SLOWER for small
        # matrices (RS(6,3): 18.5 vs 22.7 GB/s at the default tile), so
        # the sweep keeps the default
        t0 = time.perf_counter()
        g, _ = bench_kernel(k, m, n, kernel_reps)
        last_kernel_s[0] = max(45.0, time.perf_counter() - t0)
        sweep[f"{k},{m}"] = round(g, 2)
        t = _phase(f"kernel sweep {k},{m}", t)

    if not budget_ok(90.0):
        fused = {"skipped": True}
    else:
        fused = bench_fused(work, coder, vol_size)
        t = _phase("fused pipeline", t)

    # --- optional, D2H-bound phases (disk-mode encode writes 4/14 of the
    # volume back through the degraded link; rebuild writes 4 shards) ---
    disk_phase_start = time.perf_counter()
    n_batches = max(vol_size // batch, 1)
    est_d2h_s = (n_batches * d2h_lat_s
                 + (0.4 * vol_size / 1e9) / max(d2h_gbps, 1e-6))
    disk_feasible = (est_d2h_s < DISK_DEADLINE_S
                     and (time.perf_counter() - started + est_d2h_s + 120
                          < HARD_BUDGET_S))

    disk_gbps = None
    rebuild_p50 = None
    rebuild_gbps = None
    times = []
    if disk_feasible:
        t0 = time.perf_counter()
        pipeline.stream_encode(base, coder, batch_size=batch)
        cold_s = time.perf_counter() - t0
        t = _phase("encode (disk sink, cold)", t)
        # steady-state pass only if the link leaves room; else report the
        # cold number (includes the file-mode kernel compile)
        if (time.perf_counter() - disk_phase_start + est_d2h_s
                < DISK_DEADLINE_S):
            for i in range(14):
                os.remove(base + ec.to_ext(i))
            t0 = time.perf_counter()
            pipeline.stream_encode(base, coder, batch_size=batch)
            disk_gbps = vol_size / (time.perf_counter() - t0) / 1e9
            t = _phase("encode timed (disk sink)", t)
        else:
            disk_gbps = vol_size / cold_s / 1e9
        file_digest = pipeline.parity_file_digest(base)
        if file_digest.tolist() != want_digest.tolist():
            raise AssertionError(
                f"parity files {file_digest} != host digest {want_digest}")

        # rebuild p50 (config 3): 4 missing shards from 10 survivors;
        # first pass also warms the reconstruction kernel. When the link
        # budget cuts the timed reps, the cold (compile-inclusive) pass
        # still reports rather than a null.
        victims = [0, 3, 7, 12]
        cold_rebuild_s = None
        for rep in range(rebuild_reps + 1):
            for v in victims:
                os.remove(base + ec.to_ext(v))
            t0 = time.perf_counter()
            pipeline.stream_rebuild(base, coder, batch_size=batch)
            if rep == 0:
                cold_rebuild_s = time.perf_counter() - t0
            else:
                times.append(time.perf_counter() - t0)
            if time.perf_counter() - disk_phase_start > REBUILD_BUDGET_S:
                break  # degraded link: stop early
        shard_size = os.path.getsize(base + ec.to_ext(0))
        if times:
            rebuild_p50 = statistics.median(times)
        elif cold_rebuild_s is not None:
            rebuild_p50 = cold_rebuild_s  # cold: includes rebuild compile
        if rebuild_p50 is not None:
            rebuild_gbps = 10 * shard_size / rebuild_p50 / 1e9
        t = _phase(f"rebuild x{len(times) + 1}", t)

    # arithmetic per input byte at RS(k=10,m): the bitplane matmul does
    # 2*(8m)(8k) int8 MACs per k-byte column = 128*m ops/input byte; HBM
    # sees (k+m)/k bytes per input byte (bytes in + parity out, VMEM-fused)
    ops_per_s = 128 * 4 * kernel_gbps * 1e9
    hbm_gbps = 1.4 * kernel_gbps

    print(json.dumps({
        "metric": ("ec.encode pipeline GB/s/chip "
                   "(disk -> H2D -> kernel, device parity sink)"),
        "value": round(sink_gbps, 2),
        "unit": "GB/s",
        "vs_baseline": round(sink_gbps / BASELINE_GBPS, 3),
        "extra": {
            "backend": backend,
            "volume_bytes": vol_size,
            "digest_verified": "vs independent host coder",
            "pipeline_disk_gbps": (round(disk_gbps, 2)
                                   if disk_gbps is not None else None),
            "disk_phase_skipped_reason": (
                None if disk_feasible else
                f"estimated {est_d2h_s:.0f}s of D2H on a "
                f"{d2h_gbps:.3f} GB/s link with {d2h_lat_s:.2f}s/op "
                f"latency"),
            "kernel": {
                "gbps": round(kernel_gbps, 2),
                "vs_target": round(kernel_gbps / BASELINE_GBPS, 3),
                "n": kernel_n, "reps": kernel_reps, "rounds": 3,
                "spread_pct": round(kernel_spread * 100, 1),
                "tile_sweep_gbps": tile_sweep,
                "mxu_fraction": round(ops_per_s / 394e12, 4),
                "hbm_fraction": round(hbm_gbps / 819, 4),
                "bound": ("VPU (bitplane expand/repack); MXU and HBM "
                          "fractions show neither is near peak"),
            },
            "rebuild_p50_s": (round(rebuild_p50, 3)
                              if rebuild_p50 is not None else None),
            "rebuild_reps_used": len(times),
            "rebuild_is_cold": rebuild_p50 is not None and not times,
            "rebuild_gbps": (round(rebuild_gbps, 2)
                             if rebuild_gbps is not None else None),
            "sweep_kernel_gbps": sweep,
            "fused_compact_gzip_rs": fused,
            "system_req_s": system,
            "disk_needle_map": needle_map,
            "link_h2d_gbps": round(h2d_gbps, 3),
            "link_d2h_gbps": round(d2h_gbps, 3),
            "link_d2h_latency_s": round(d2h_lat_s, 3),
            "note": ("value = device-parity-sink pipeline (disk read + H2D "
                     "+ kernel overlapped; 16B digest returns per batch, "
                     "verified against an independent host-coder digest of "
                     "the same volume). pipeline_disk_gbps is the same "
                     "schedule writing all 14 shard files; on a tunneled "
                     "dev chip it is bound by link_d2h_gbps, which parity "
                     "must cross to reach disk."),
        },
    }))


if __name__ == "__main__":
    main()
